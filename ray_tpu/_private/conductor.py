"""Conductor: the cluster control plane.

TPU-native consolidation of the reference's GCS server + raylet scheduling
(/root/reference/src/ray/gcs/gcs_server/gcs_server.h:78 composition — node /
actor / job / placement-group / KV / health managers — and
src/ray/raylet/scheduling/cluster_task_manager.cc). Per SURVEY.md §7 we merge
the two: TPU slices are homogeneous and topology-known, so a single authority
holds the resource view and grants worker leases directly; there is no
spillback protocol. Workers are leased to submitters which then push tasks
*directly* worker-to-worker (the reference's direct task transport design,
direct_task_transport.h:75 — kept, because it is the right hot path).

Responsibilities:
- worker pool per node: pre-start/spawn Python worker processes, lease/return
  (reference worker_pool.h:156 / PopWorker :343)
- actor management: creation (conductor-mediated like gcs_actor_manager.cc:255),
  named actors, restart-on-death with max_restarts
- internal KV + simple pubsub (gcs_kv_manager.cc)
- placement groups: atomic bundle reservation (PACK/SPREAD/STRICT_*)
- health: reap dead worker processes, publish deaths, restart actors
- task-event buffer for the state API (gcs_task_manager.cc)
"""
from __future__ import annotations

import collections
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .. import exceptions as exc
from . import serialization
from .ids import ActorID, NodeID, PlacementGroupID, WorkerID
from .rpc import ClientPool, RpcServer

def _worker_start_timeout() -> float:
    from .config import config

    return config.worker_start_timeout


_RESILIENCE_COUNTER = None
_TTR_GAUGE = None


def _resilience_metrics():
    """Lazy Prometheus-surface twins of the conductor's resilience
    counters (created on first event so importing the conductor never
    spawns a metrics pusher)."""
    global _RESILIENCE_COUNTER, _TTR_GAUGE
    if _RESILIENCE_COUNTER is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _RESILIENCE_COUNTER = Counter(
            "ray_tpu_resilience_events_total",
            "resilience events by kind (preemption/restart/quarantine/"
            "grace_checkpoint/chaos/recovery)", tag_keys=("kind",))
        _TTR_GAUGE = Gauge(
            "ray_tpu_time_to_recovery_seconds",
            "first failure -> successful fit, most recent recovery")
    return _RESILIENCE_COUNTER, _TTR_GAUGE


def _chips_needed(resources: Dict[str, float]) -> int:
    """Whole-chip count a lease pins to the worker via TPU_VISIBLE_CHIPS
    (reference accelerators/tpu.py:30). Fractional TPU shares only
    resource-count — chip binding is per-process (libtpu is single-client
    per chip), so there is nothing meaningful to pin below one chip."""
    for k, v in resources.items():
        if k == "TPU" or k.endswith("_TPU"):
            if v >= 1 and float(v).is_integer():
                return int(v)
    return 0


@dataclass
class WorkerRecord:
    worker_id: str
    node_id: str
    address: Optional[Tuple[str, int]] = None
    pid: Optional[int] = None
    state: str = "STARTING"  # STARTING | IDLE | BUSY | ACTOR | DEAD
    proc: Optional[subprocess.Popen] = None
    resources: Dict[str, float] = field(default_factory=dict)  # held while leased
    # node whose resources the current lease took (an autoscaled accounting
    # node may differ from the spawn node on this single-host runtime)
    lease_node_id: Optional[str] = None
    # lease resources parked while the worker blocks in get()/wait()
    # (reference: raylet releases blocked workers' resources)
    blocked_resources: Optional[Dict[str, float]] = None
    # TPU chips this process was bound to at spawn (TPU_VISIBLE_CHIPS);
    # chips stay bound for the process lifetime — its TPU runtime owns the
    # devices — and return to the node pool only on death
    chip_ids: Optional[Tuple[int, ...]] = None
    # set on records rebuilt from a persistence snapshot: liveness is
    # unknown until the worker re-registers (fills pid) or a grace period
    # expires (presumed dead with the old conductor)
    restored_at: Optional[float] = None
    # why the worker died, when the runtime knows (e.g. "oom: ..." from
    # the memory monitor) — submitters query this to raise a typed error
    death_cause: Optional[str] = None
    # set before a deliberate kill (ray_tpu.kill, node deregistration,
    # gang teardown): the death must not charge the failure-domain
    # tracker — only UNEXPECTED deaths count toward quarantine
    expected_death: bool = False


@dataclass
class ActorRecord:
    actor_id: str
    name: Optional[str]
    namespace: str
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    worker_id: Optional[str] = None
    address: Optional[Tuple[str, int]] = None
    spec: Optional[bytes] = None  # pickled (cls, args, kwargs, options)
    restarts_remaining: int = 0
    max_task_retries: int = 0
    resources: Dict[str, float] = field(default_factory=dict)
    death_cause: Optional[str] = None
    num_restarts: int = 0
    placement_group_id: Optional[str] = None
    # "DEFAULT" | "SPREAD" | ("NODE_AFFINITY", node_id, soft)
    scheduling_strategy: Any = "DEFAULT"


@dataclass
class PlacementGroupRecord:
    pg_id: str
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = "CREATED"  # CREATED | REMOVED
    name: Optional[str] = None
    # node_id per bundle (parallel to `bundles`) — the scheduler's
    # placement decision (reference bundle_scheduling_policy.h)
    assignments: List[str] = field(default_factory=list)


@dataclass
class NodeRecord:
    node_id: str
    total: Dict[str, float]
    available: Dict[str, float]
    address: Optional[Tuple[str, int]] = None  # node agent RPC (None = inline)
    alive: bool = True
    last_heartbeat: float = 0.0  # agent nodes only (address is not None)
    # physical TPU chip ids not bound to any live worker process
    # (reference: accelerators/tpu.py:30 TPU_VISIBLE_CHIPS partitioning)
    free_chips: List[int] = field(default_factory=list)

    @property
    def has_agent(self) -> bool:
        return self.address is not None


class ConductorHandler:
    """RPC handler — every public method is remotely callable."""

    # block-by-design handlers (waiting IS their job): exempt from the
    # RPC server's slow-handler warning — see RpcServer warn_slow.
    # create_actor blocks on the same capacity wait via _place_actor.
    _slow_ok_methods = frozenset({"lease_worker", "create_actor"})

    def __init__(self, resources: Dict[str, float], session_dir: str,
                 worker_env: Optional[Dict[str, str]] = None):
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._nodes: Dict[str, NodeRecord] = {}
        self._workers: Dict[str, WorkerRecord] = {}
        self._actors: Dict[str, ActorRecord] = {}
        self._named_actors: Dict[Tuple[str, str], str] = {}  # (ns, name) -> id
        self._pgs: Dict[str, PlacementGroupRecord] = {}
        self._kv: Dict[str, Dict[bytes, bytes]] = {}
        self._subs: Dict[str, List[Tuple[str, int]]] = {}  # channel -> addrs
        self._task_events: List[Dict[str, Any]] = []
        self._spans: List[Dict[str, Any]] = []  # tracing span table
        # flight recorder: run_id -> {"steps": {step -> {rank -> record}},
        # "updated": ts} ring buffers fed by StepTimer.flush batches
        self._train_runs: Dict[str, Dict[str, Any]] = {}
        self._session_dir = session_dir
        self._worker_env = dict(worker_env or {})
        self._clients = ClientPool()
        self._stopped = False
        self._waiting_leases = 0
        # Parked lease_worker calls, each on its OWN condition sharing
        # self._lock. Capacity events wake exactly ONE waiter (rotating
        # for fairness) and a successful grant cascades to the next —
        # notify_all here caused a measured 6x throughput collapse once
        # waiters outnumbered workers (every return_worker woke every
        # parked waiter into a full rescan). The 0.1s wait timeout in
        # _lease_locked remains the liveness net for any missed wakeup.
        self._lease_waiter_cvs: "collections.deque" = collections.deque()
        # resource shapes of leases currently blocked (autoscaler signal)
        self._pending_demand: List[Tuple[float, Dict[str, float]]] = []
        self.address: Optional[Tuple[str, int]] = None  # set by Conductor

        head = NodeRecord(node_id=NodeID().hex(), total=dict(resources),
                          available=dict(resources),
                          free_chips=list(range(int(resources.get("TPU", 0)))))
        self._nodes[head.node_id] = head
        self._head_node_id = head.node_id

        # Failure-domain quarantine + resilience event log
        # (ray_tpu.resilience): unexpected worker deaths charge their
        # host's decayed score; hosts over the threshold are excluded
        # from lease grants and bundle assignment. The head is exempt
        # from AUTO-quarantine (it is the control plane's own pool —
        # excluding it on a single-host runtime would deadlock every
        # lease), though an operator quarantine_node still pins it.
        from ray_tpu.resilience.domains import FailureDomainTracker
        from .config import config as _config

        self._fd_tracker = FailureDomainTracker(
            threshold=_config.quarantine_threshold,
            half_life_s=_config.quarantine_halflife_s,
            exempt=(head.node_id,))
        self._resilience_events: List[Dict[str, Any]] = []
        self._resilience_counters: Dict[str, int] = {}
        self._last_ttr_s: Optional[float] = None

        # Live weight fabric (ray_tpu.weights): versioned manifests of
        # sharded in-memory weight publications. Chunks stay in their
        # producers' object stores (ownership model — no bytes here);
        # the registry holds only metadata and is the single commit
        # authority: a version becomes visible to subscribers atomically
        # when its LAST host fragment lands.
        # committed: name -> {version -> manifest}; pending: (name,
        # version) -> in-flight publish (reaped after weights_publish_ttl_s)
        self._weights_committed: Dict[str, Dict[int, Dict[str, Any]]] = {}
        self._weights_pending: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._weight_events: List[Dict[str, Any]] = []

        # Paged KV prefix cache (models/kvcache.py): serving engines
        # push per-engine stat snapshots + prefix-hit/evict markers;
        # the conductor only aggregates (no KV bytes ever land here).
        self._kvcache_stats: Dict[str, Dict[str, Any]] = {}
        self._kvcache_events: List[Dict[str, Any]] = []

        # Online learning loop (ray_tpu.online): sampler actors, the
        # rollout buffer, and the learner each push stat snapshots
        # (keyed by component id) + rollout/publish/swap/ingest markers;
        # the conductor only aggregates — rollout payloads never land
        # here.
        self._online_stats: Dict[str, Dict[str, Any]] = {}
        self._online_events: List[Dict[str, Any]] = []

        # Disaggregated serving (serve/disagg.py): prefill servers,
        # decode servers, and routers push stat snapshots (keyed by
        # component id) + kv_publish/kv_transfer/shed markers; the
        # conductor only aggregates — KV payload never lands here.
        self._disagg_stats: Dict[str, Dict[str, Any]] = {}
        self._disagg_events: List[Dict[str, Any]] = []

        # Global KV plane (serve/kvplane.py): replicas push tier-2
        # arena / tier-3 adoption snapshots + spill/adopt/directory
        # markers, and the PREFIX DIRECTORY lives here — (namespace,
        # digest-chain) -> holder + chunk descriptor, metadata only
        # (the weight-fabric registry pattern: atomic commit, TTL reap,
        # keep-last-K GC). KV payload bytes never land here; they ride
        # the chunk fabric between replicas.
        self._kvplane_stats: Dict[str, Dict[str, Any]] = {}
        self._kvplane_events: List[Dict[str, Any]] = []
        self._kvplane_dir: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._kvplane_dir_counters: Dict[str, int] = {
            k: 0 for k in ("publishes", "republishes", "lookups",
                           "directory_hits", "directory_misses",
                           "reaped", "gced", "unpublished")}

        # Serving autoscaler (serve/autoscale.py): policy loops push
        # status snapshots (targets, decisions, replica-seconds) +
        # scale_up/scale_down/drain markers; the conductor only
        # aggregates. util.state.autoscaler_status(), `ray_tpu
        # autoscale`, and /api/autoscale all read the same aggregate.
        self._autoscale_stats: Dict[str, Dict[str, Any]] = {}
        self._autoscale_events: List[Dict[str, Any]] = []

        # Serving-plane fault tolerance (serve/disagg.py failover +
        # serve/autoscale.py self-healing): routers push failover/shed
        # accounting, healers push death/replacement/breaker counters.
        # The failover/replace/breaker_trip instant markers ride the
        # RESILIENCE event log (they ARE recovery events); this roster
        # feeds util.state.servefault_status(), `ray_tpu servefault`,
        # and /api/servefault with one set of numbers.
        self._servefault_stats: Dict[str, Dict[str, Any]] = {}

        # Multi-tenant LoRA serving (serve/lora.py): adapter pools push
        # paging snapshots (hits/misses/evictions/swaps, residents),
        # routers push per-tenant request counters; page_in/evict/swap
        # markers feed the merged timeline's `lora` lane. One aggregate
        # feeds util.state.lora_status(), `ray_tpu lora`, /api/lora.
        self._lora_stats: Dict[str, Dict[str, Any]] = {}
        self._lora_events: List[Dict[str, Any]] = []

        # HTTP front door (serve/gateway.py): gateway replicas push
        # request/class/code counters + TTFT windows; QoS gates and
        # routers push accept/first_byte/preempt/rate_limit/disconnect
        # markers for the merged timeline's `gateway` lane. One
        # aggregate feeds util.state.gateway_status(), `ray_tpu
        # gateway`, and /api/gateway.
        self._gateway_stats: Dict[str, Dict[str, Any]] = {}
        self._gateway_events: List[Dict[str, Any]] = []

        # Per-request flight recorder (observability/requests.py):
        # stores push retention/outcome counters + compact latency
        # summaries (p99 attribution population) and each KEPT trace
        # rides the event log so `ray_tpu requests --trace <id>` and
        # the merged timeline's `requests` lane can replay a request's
        # phase spans. One aggregate feeds
        # util.state.requesttrace_status(), `ray_tpu requests`, and
        # /api/requesttrace.
        self._requesttrace_stats: Dict[str, Dict[str, Any]] = {}
        self._requesttrace_events: List[Dict[str, Any]] = []

        # Step-time oracle (observability.roofline): predicted step-time
        # breakdowns keyed by layout + predicted-vs-measured validation
        # records (residuals, fitted calibration). One aggregate feeds
        # util.state.oracle_status(), `ray_tpu oracle`, /api/oracle, and
        # the merged timeline's predicted-step-time counter track.
        self._oracle_predictions: Dict[str, Dict[str, Any]] = {}
        self._oracle_validations: List[Dict[str, Any]] = []
        self._oracle_events: List[Dict[str, Any]] = []

        # MPMD pipelines (ray_tpu.mpmd): stage registry (a pipeline
        # flips "formed" atomically when its LAST stage registers —
        # the weights-fragment commit pattern) + the channel mailbox.
        # The mailbox holds metadata-only descriptors of activation
        # chunks living in the SENDER's object store; payload bytes
        # never land here.
        self._pipelines: Dict[str, Dict[str, Any]] = {}
        self._pipeline_mailbox: Dict[str, Dict[str, Any]] = {}
        self._pipeline_events: List[Dict[str, Any]] = []

        # Durable control-plane tables (reference: GCS Redis-persisted
        # tables, gcs_server.h:103-110 / gcs_table_storage.cc). A snapshot
        # in the session dir lets a restarted conductor recover KV, named
        # actors, placement groups, and job metadata; live workers/agents
        # re-register themselves on their next periodic announce.
        self._persist_path = os.path.join(session_dir, "conductor_state.pkl")
        self._dirty = False
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._restore_state()

        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="conductor-monitor", daemon=True)

    # ------------------------------------------------------------------ nodes

    def register_node(self, node_id: str, resources: Dict[str, float],
                      address: Optional[Tuple[str, int]] = None) -> None:
        """address is the node's agent RPC endpoint; None registers an
        accounting-only node served by the head's worker pool (autoscaler
        test double, reference FakeMultiNodeProvider)."""
        with self._cv:
            # chips already announced by surviving workers of this node
            # (conductor-restart path: a worker's heartbeat may precede
            # its node agent's re-register) must not return to the pool
            bound = {c for w in self._workers.values()
                     if w.node_id == node_id and w.state != "DEAD"
                     for c in (w.chip_ids or ())}
            self._nodes[node_id] = NodeRecord(
                node_id=node_id, total=dict(resources),
                available=dict(resources),
                address=tuple(address) if address else None,
                last_heartbeat=time.monotonic(),
                free_chips=[c for c in range(int(resources.get("TPU", 0)))
                            if c not in bound])
            self._reapply_pg_reservations(node_id)
            self._notify_all_locked()

    def _reapply_pg_reservations(self, node_id: str) -> None:
        """A (re-)registered node's record starts with full availability;
        re-reserve any live placement-group bundles assigned to it (the
        conductor-restart path — PGs are persisted, nodes are not). Must
        hold the lock."""
        node = self._nodes[node_id]
        for pg in self._pgs.values():
            if pg.state != "CREATED":
                continue
            mine = [b for b, nid in zip(pg.bundles, pg.assignments or ())
                    if nid == node_id]
            if not mine:
                continue
            pk0 = f"_pg_{pg.pg_id}_"
            if any(k.startswith(pk0) for k in node.total):
                continue  # already applied (plain re-register)
            for b in mine:
                self._acquire_resources(node, b)
                for k, v in b.items():
                    pk = pk0 + k
                    node.total[pk] = node.total.get(pk, 0) + v
                    node.available[pk] = node.available.get(pk, 0) + v

    def node_heartbeat(self, node_id: str,
                       dead_worker_ids: Optional[List[str]] = None,
                       death_causes: Optional[Dict[str, str]] = None
                       ) -> bool:
        """Agent liveness + push-reported worker deaths (the conductor
        cannot poll pids on remote hosts). death_causes carries typed
        causes (e.g. the agent's memory monitor OOM kills)."""
        dead_recs: List[WorkerRecord] = []
        with self._cv:
            n = self._nodes.get(node_id)
            if n is None:
                return False  # unknown (e.g. after conductor restart)
            n.last_heartbeat = time.monotonic()
            n.alive = True
            for wid, cause in (death_causes or {}).items():
                w = self._workers.get(wid)
                if w is not None and w.death_cause is None:
                    w.death_cause = cause
            for wid in dead_worker_ids or []:
                w = self._workers.get(wid)
                if w is not None and w.state != "DEAD":
                    w.state = "DEAD"
                    self._release_resources(self._lease_release_node(w),
                                            w.resources)
                    w.resources = {}
                    self._free_worker_chips(w)
                    dead_recs.append(w)
                    if w.address:
                        self._clients.invalidate(w.address)
            self._notify_all_locked()
        for w in dead_recs:
            self._on_worker_death(w)
        return True

    def deregister_node(self, node_id: str, force: bool = False) -> bool:
        """Remove a non-head node. Without force (autoscaler scale-down)
        only an idle node may leave; with force (NodeAgent.stop — the host
        is going away regardless) its workers are declared dead, their
        leases freed, and their actors sent through the restart path."""
        dead: List[WorkerRecord] = []
        with self._cv:
            if node_id == self._head_node_id:
                return False
            n = self._nodes.get(node_id)
            if n is None:
                return False
            if not force and any(n.available.get(k, 0.0) < v
                                 for k, v in n.total.items()):
                return False  # leases still hold its resources
            for w in self._workers.values():
                if w.node_id == node_id and w.state != "DEAD":
                    w.state = "DEAD"
                    w.expected_death = True  # host is leaving on purpose
                    self._release_resources(self._lease_release_node(w),
                                            w.resources)
                    w.resources = {}
                    self._free_worker_chips(w)
                    dead.append(w)
                    if w.address:
                        self._clients.invalidate(w.address)
            del self._nodes[node_id]
            self._notify_all_locked()
        for w in dead:
            self._on_worker_death(w)
        return True

    def _free_worker_chips(self, w: WorkerRecord) -> None:
        """Return a dead worker's bound chips to its node's pool. Must
        hold the lock."""
        if not w.chip_ids:
            return
        n = self._nodes.get(w.node_id)
        if n is not None:
            n.free_chips.extend(w.chip_ids)
        w.chip_ids = None

    def _reclaim_chips_after_exit(self, w: WorkerRecord) -> None:
        """Terminate `w` and return its chips to the node pool only once
        the process is confirmed gone (reaped locally, or its RPC port
        stopped answering remotely). Immediate _free_worker_chips here
        would let a successor bind the same TPU_VISIBLE_CHIPS while the
        old owner's TPU runtime still holds the devices."""
        def confirmed_gone() -> bool:
            if w.proc is not None:
                try:
                    if w.proc.poll() is None:
                        w.proc.terminate()
                        try:
                            w.proc.wait(timeout=8.0)
                        except subprocess.TimeoutExpired:
                            w.proc.kill()
                            w.proc.wait(timeout=8.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
                return w.proc.poll() is not None
            if w.address:
                addr = tuple(w.address)
                try:
                    self._clients.get(addr).call("shutdown_worker",
                                                 timeout=5.0)
                except Exception:  # noqa: BLE001 — may already be gone
                    pass
                deadline = time.monotonic() + 15.0
                while time.monotonic() < deadline:
                    try:
                        self._clients.get(addr).call("ping", timeout=1.0)
                    except Exception:  # noqa: BLE001 — port closed
                        return True
                    time.sleep(0.2)
                return False
            return True  # no process handle and no address: nothing runs

        def reap():
            # Free the chips ONLY once the owner is verifiably gone. A
            # wedged worker (e.g. stuck in a native call) keeps its chips
            # parked — leaked capacity beats a libtpu double-bind. Keep
            # retrying with backoff; most stragglers exit eventually.
            backoff = 1.0
            while not self._stopped:
                if confirmed_gone():
                    with self._cv:
                        self._free_worker_chips(w)
                        self._notify_all_locked()
                    return
                time.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

        threading.Thread(target=reap, daemon=True,
                         name="chip-reaper").start()

    def cluster_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for n in self._nodes.values():
                if not n.alive:
                    continue
                for k, v in n.total.items():
                    out[k] = out.get(k, 0) + v
            return out

    def available_resources(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {}
            for n in self._nodes.values():
                if not n.alive:
                    continue
                for k, v in n.available.items():
                    out[k] = out.get(k, 0) + v
            return out

    def nodes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"node_id": n.node_id, "alive": n.alive, "total": n.total,
                     "available": n.available,
                     "head": n.node_id == self._head_node_id,
                     "address": list(n.address) if n.address else None}
                    for n in self._nodes.values()]

    # ---------------------------------------------------------------- workers

    def register_worker(self, worker_id: str, address: Tuple[str, int],
                        pid: int, node_id: Optional[str] = None,
                        chip_ids: Optional[Tuple[int, ...]] = None) -> bool:
        """Returns False to tell the worker to shut itself down (its chip
        binding conflicts with the conductor's post-restart view)."""
        with self._cv:
            w = self._workers.get(worker_id)
            if w is not None and w.state == "DEAD":
                # a worker we already wrote off (e.g. chips reclaimed)
                # re-announcing after a partition: it must not run — its
                # chips may already be bound elsewhere
                return False
            if w is None:
                w = WorkerRecord(worker_id=worker_id,
                                 node_id=node_id or self._head_node_id)
                self._workers[worker_id] = w
            if node_id:
                w.node_id = node_id
            w.address = tuple(address)
            w.pid = pid
            w.restored_at = None  # liveness confirmed
            if chip_ids and not w.chip_ids:
                # A surviving chip worker re-announcing to a restarted
                # conductor (which reinitialized free_chips to the full
                # range): its TPU runtime still owns those devices, so
                # subtract them from the pool. If another live worker was
                # already bound to any of them meanwhile, the survivor
                # must die — libtpu is single-client per chip.
                chips = tuple(int(c) for c in chip_ids)
                n = self._nodes.get(w.node_id)
                taken = {c for rec in self._workers.values()
                         if rec is not w and rec.state != "DEAD"
                         for c in (rec.chip_ids or ())}
                if taken & set(chips):
                    w.state = "DEAD"
                    self._notify_all_locked()
                    return False
                if n is not None:
                    n.free_chips = [c for c in n.free_chips
                                    if c not in chips]
                w.chip_ids = chips
            if w.state == "STARTING":
                w.state = "IDLE"
            self._notify_all_locked()
            return True

    def _spawn_worker(self, env_extra: Optional[Dict[str, str]] = None,
                      node: Optional[NodeRecord] = None) -> WorkerRecord:
        """Start a worker (reference: WorkerPool PopWorker spawn path,
        worker_pool.h:343). Head/accounting nodes spawn locally; agent
        nodes get an RPC to their NodeAgent (the raylet-equivalent).
        Caller holds self._lock — the lease loop cv-waits for the new
        worker to register back."""
        from .worker_spawn import spawn_worker_process

        worker_id = WorkerID().hex()
        if node is not None and node.has_agent:
            w = WorkerRecord(worker_id=worker_id, node_id=node.node_id)
            self._workers[worker_id] = w
            agent_addr, env = node.address, dict(env_extra or {})

            def ask_agent():
                try:
                    self._clients.get(agent_addr).call(
                        "spawn_worker", worker_id, env or None,
                        timeout=30.0)
                except Exception:
                    with self._cv:
                        w.state = "DEAD"
                        self._free_worker_chips(w)
                        self._notify_all_locked()

            # RPC outside the conductor lock; the lease loop cv-waits for
            # the worker to register back.
            threading.Thread(target=ask_agent, daemon=True).start()
            return w
        proc = spawn_worker_process(
            worker_id, self.address, self._session_dir,
            worker_env=self._worker_env, env_extra=env_extra,
            node_id=self._head_node_id)
        w = WorkerRecord(worker_id=worker_id, node_id=self._head_node_id,
                         proc=proc)
        self._workers[worker_id] = w
        return w

    def _acquire_resources(self, node: NodeRecord, req: Dict[str, float]) -> bool:
        for k, v in req.items():
            if k.startswith("_pg_") and k not in node.available:
                # bundle pool lives elsewhere: even a ZERO-resource PG
                # lease (0-CPU actors) must bind to the bundle's node —
                # gang placement and failure-domain accounting both
                # depend on the lease landing where the bundle was
                # reserved
                return False
            if node.available.get(k, 0.0) + 1e-9 < v:
                return False
        for k, v in req.items():
            node.available[k] = node.available.get(k, 0.0) - v
        return True

    def _release_resources(self, node: Optional[NodeRecord],
                           req: Dict[str, float]) -> None:
        if node is None:
            return
        for k, v in req.items():
            node.available[k] = node.available.get(k, 0.0) + v

    def lease_worker(self, resources: Dict[str, float],
                     placement_group_id: Optional[str] = None,
                     timeout: Optional[float] = None,
                     strategy: str = "DEFAULT",
                     arg_locations=None) -> Tuple[str, Tuple[str, int]]:
        """Grant an idle worker (spawning if below capacity), holding
        `resources` against the node until return_worker. strategy
        DEFAULT packs (head-first, biased toward the node holding the
        most argument bytes — reference lease_policy.cc); SPREAD prefers
        the emptiest node; ("NODE_AFFINITY", node_id, soft) pins
        (reference node_affinity_scheduling_policy.cc).

        `arg_locations`: [(holder_address, nbytes), ...] locality hints
        from the submitter; addresses not belonging to a registered
        worker (e.g. a driver) are ignored."""
        deadline = time.monotonic() + (timeout if timeout is not None
                                       else _worker_start_timeout())
        resources = dict(resources or {})
        resources.setdefault("CPU", 1.0)
        if placement_group_id is not None:
            # resources come out of the PG's pre-reserved bundle pool
            resources = {f"_pg_{placement_group_id}_{k}": v
                         for k, v in resources.items()}
        demand_token = (time.time(), dict(resources))
        with self._cv:
            self._waiting_leases += 1
            self._pending_demand.append(demand_token)
            try:
                return self._lease_locked(resources, deadline, strategy,
                                          arg_locations)
            finally:
                self._waiting_leases -= 1
                self._pending_demand.remove(demand_token)

    def get_rpc_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-method dispatch latency of the conductor's RPC server —
        the control plane's instrumented_io_context analog (reference
        src/ray/common/asio/instrumented_io_context.h stats)."""
        srv = getattr(self, "_rpc_server", None)
        return srv.handler_stats() if srv is not None else {}

    def get_pending_demand(self) -> List[Dict[str, Any]]:
        """Resource shapes of leases currently waiting, with wait age —
        the autoscaler's scale-up signal (reference LoadMetrics /
        gcs_autoscaler_state_manager.cc)."""
        now = time.time()
        with self._lock:
            return [{"resources": dict(res), "age_s": now - t0}
                    for t0, res in self._pending_demand]

    def _lease_release_node(self, w: WorkerRecord) -> Optional[NodeRecord]:
        """The node to credit a worker's held resources back to, or None
        when the node was deregistered mid-lease (its resources died with
        it — crediting another node would inflate the pool)."""
        return self._nodes.get(w.lease_node_id or w.node_id) \
            or self._nodes.get(w.node_id)

    def _wake_lease_waiter_locked(self, skip=None) -> None:
        """Wake ONE parked lease waiter (rotating so consecutive events
        spread across waiters). `skip` excludes the granting thread's own
        cv during the grant cascade — notifying it would be a wasted
        wakeup (it is leaving) and the remaining waiters would sit out
        the full 0.1s poll. Must hold self._lock."""
        for _ in range(len(self._lease_waiter_cvs)):
            cv = self._lease_waiter_cvs[0]
            self._lease_waiter_cvs.rotate(-1)
            if cv is not skip:
                cv.notify()
                return

    def _notify_all_locked(self) -> None:
        """State-change fanout: wake shared-cv waiters (actor state, PG,
        spawn waits) plus one parked lease waiter. Must hold self._lock."""
        self._cv.notify_all()
        self._wake_lease_waiter_locked()

    def _lease_locked(self, resources, deadline,
                      strategy: str = "DEFAULT", arg_locations=None):
            affinity = None
            if isinstance(strategy, (tuple, list)) and strategy \
                    and strategy[0] == "NODE_AFFINITY":
                affinity = (str(strategy[1]), bool(strategy[2]))
            my_cv = threading.Condition(self._lock)
            self._lease_waiter_cvs.append(my_cv)
            try:
                return self._lease_wait_locked(resources, deadline, strategy,
                                               arg_locations, affinity, my_cv)
            finally:
                try:
                    self._lease_waiter_cvs.remove(my_cv)
                except ValueError:
                    pass

    def _lease_wait_locked(self, resources, deadline, strategy,
                           arg_locations, affinity, my_cv):
            while True:
                if self._stopped:
                    raise RuntimeError("conductor stopped")
                # head first, then any registered (e.g. autoscaled) node —
                # workers run on this host either way; remote nodes are
                # resource-accounting entries (single-host runtime).
                head = self._nodes[self._head_node_id]
                nodes = [head] + [n for nid, n in self._nodes.items()
                                  if nid != self._head_node_id and n.alive]
                pinned = None
                if affinity is not None:
                    pinned = self._affinity_nodes_locked(
                        affinity, resources)
                if pinned is None:
                    # failure-domain quarantine + preemption drain: a
                    # host that keeps killing gangs, or one about to be
                    # reclaimed, must not receive new leases. When EVERY
                    # node is excluded the filter yields — a degraded
                    # grant beats a cluster-wide deadlock. An explicit
                    # NODE_AFFINITY pin (pinned) beats quarantine.
                    kept = [n for n in nodes
                            if not self._fd_tracker.is_excluded(n.node_id)]
                    if kept:
                        nodes = kept
                if pinned is not None:
                    nodes = pinned
                elif strategy == "SPREAD":
                    # emptiest node first (reference SPREAD policy,
                    # scheduling/policy/spread_scheduling_policy.cc) —
                    # the DEFAULT order above is pack/head-first
                    def busy(n: NodeRecord) -> int:
                        return sum(1 for w in self._workers.values()
                                   if w.state in ("BUSY", "ACTOR")
                                   and (w.lease_node_id or w.node_id)
                                   == n.node_id)

                    nodes.sort(key=busy)
                elif arg_locations:
                    # data locality: stable-sort candidates by argument
                    # bytes resident on each node, most first (reference
                    # core_worker/lease_policy.cc LocalityAwareLeasePolicy)
                    score = self._locality_scores_locked(arg_locations)
                    if score:
                        nodes.sort(
                            key=lambda n: -score.get(n.node_id, 0.0))
                acquired = None
                for node in nodes:
                    if self._acquire_resources(node, resources):
                        acquired = node
                        break
                if acquired is not None:
                    w = self._take_idle_or_spawn(deadline, acquired,
                                                 _chips_needed(resources))
                    if w is not None:
                        w.state = "BUSY"
                        w.resources = resources
                        w.lease_node_id = acquired.node_id
                        # grant cascade: capacity may remain (coalesced
                        # frees) — hand the baton to the next waiter
                        self._wake_lease_waiter_locked(skip=my_cv)
                        return w.worker_id, w.address
                    self._release_resources(acquired, resources)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no worker available for {resources} within timeout; "
                        f"available={head.available}")
                my_cv.wait(min(remaining, 0.1))

    def _affinity_nodes_locked(self, affinity, resources):
        """Candidate list under ("NODE_AFFINITY", node_id, soft):
        [target] while the node is alive and can ever fit the request
        (merely-busy waits, reference node_affinity semantics); soft
        degrades to None (caller keeps the default order); hard raises
        SchedulingError — failing the task beats waiting forever."""
        node_id, soft = affinity
        target = self._nodes.get(node_id)
        feasible = target is not None and target.alive and all(
            target.total.get(k, 0.0) + 1e-9 >= v
            for k, v in resources.items() if not k.startswith("_pg_"))
        if feasible:
            # _pg_-prefixed keys exist only in `available` on the node(s)
            # holding the reservation: a pin to a node without the bundle
            # can never succeed and must not wait out the lease timeout
            feasible = all(k in target.available for k in resources
                           if k.startswith("_pg_"))
        if feasible:
            return [target]
        if soft:
            return None
        raise exc.SchedulingError(
            f"NodeAffinity(node_id={node_id!r}, soft=False) cannot be "
            "satisfied: node is "
            + ("dead or unknown" if target is None or not target.alive
               else f"too small for {resources}"))

    def _locality_scores_locked(self, arg_locations) -> Dict[str, float]:
        """node_id -> argument bytes held there, from (address, nbytes)
        hints. Unknown addresses (drivers, departed workers) score 0."""
        addr_to_node = {tuple(w.address): (w.lease_node_id or w.node_id)
                        for w in self._workers.values()
                        if w.address is not None}
        score: Dict[str, float] = {}
        for addr, nbytes in arg_locations:
            nid = addr_to_node.get(tuple(addr))
            if nid is not None:
                # unknown size still signals presence
                score[nid] = score.get(nid, 0.0) + max(float(nbytes), 1.0)
        return score

    def _spawn_node_id(self, node: NodeRecord) -> str:
        """The node whose worker pool serves a lease on `node`: agent
        nodes run their own workers; accounting nodes (autoscaler fakes,
        address=None) are served by the head's pool."""
        return node.node_id if node.has_agent else self._head_node_id

    def _take_idle_or_spawn(self, deadline: float, node: NodeRecord,
                            n_chips: int = 0) -> Optional[WorkerRecord]:
        """Must hold lock. Returns a registered IDLE worker on `node`'s
        serving pool, or None.

        n_chips > 0 requests a TPU-bound worker: its process was spawned
        with TPU_VISIBLE_CHIPS naming exactly that many chips (reference
        accelerators/tpu.py:147,161 set_current_process_visible_accelerator_ids).
        Chip workers are only reused for leases of the same chip count;
        idle chip workers with the wrong count are torn down to reclaim
        their chips when the pool runs dry."""
        pool_id = self._spawn_node_id(node)
        pool = self._nodes[pool_id]

        def idle():
            for w in self._workers.values():
                if w.state == "IDLE" and w.node_id == pool_id and \
                        len(w.chip_ids or ()) == n_chips:
                    return w
            return None

        def try_spawn_chip_worker() -> bool:
            if len(pool.free_chips) < n_chips:
                # Reclaim chips bound to idle workers of other counts.
                # Chips return to the pool only AFTER the old process has
                # verifiably exited (_reclaim_chips_after_exit): libtpu is
                # single-client per chip, so a successor spawned while the
                # old owner is still dying fails TPU init. The lease loop
                # cv-waits; the reaper's notify retries the spawn.
                prospective = len(pool.free_chips) + sum(
                    len(w.chip_ids or ()) for w in self._workers.values()
                    if w.state == "DEAD" and w.node_id == pool_id
                    and w.chip_ids)  # reclaims already in flight
                for w in list(self._workers.values()):
                    if prospective >= n_chips:
                        break
                    if w.state == "IDLE" and w.node_id == pool_id and \
                            w.chip_ids and len(w.chip_ids) != n_chips:
                        w.state = "DEAD"
                        prospective += len(w.chip_ids)
                        self._reclaim_chips_after_exit(w)
            if len(pool.free_chips) < n_chips:
                return False
            chips = tuple(sorted(pool.free_chips)[:n_chips])
            for c in chips:
                pool.free_chips.remove(c)
            w = self._spawn_worker(node=node, env_extra={
                "TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chips),
                "RAY_TPU_WORKER_FULL_SITE": "1",
                # undo the host-side workers' cpu pin: this worker owns chips
                "JAX_PLATFORMS": "",
            })
            w.chip_ids = chips
            return True

        w = idle()
        if w is not None:
            return w
        if n_chips > 0:
            spawned = try_spawn_chip_worker()
            while time.monotonic() < deadline and not self._stopped:
                w = idle()
                if w is not None:
                    return w
                if not spawned:
                    spawned = try_spawn_chip_worker()
                self._cv.wait(0.05)
            return None
        n_starting = sum(1 for w in self._workers.values()
                         if w.state == "STARTING"
                         and w.node_id == pool_id and not w.chip_ids)
        # spawn enough for every lease currently waiting (parallel cold-start)
        want = max(1, self._waiting_leases)
        for _ in range(max(0, want - n_starting)):
            self._spawn_worker(node=node)
        while time.monotonic() < deadline and not self._stopped:
            w = idle()
            if w is not None:
                return w
            self._cv.wait(0.05)
        return None

    def return_worker(self, worker_id: str) -> None:
        with self._cv:
            w = self._workers.get(worker_id)
            if w is None or w.state == "DEAD":
                return
            self._release_resources(self._lease_release_node(w), w.resources)
            w.resources = {}
            w.blocked_resources = None  # a parked lease dies with the task
            if w.state == "BUSY":
                w.state = "IDLE"
            self._notify_all_locked()

    def worker_blocked(self, worker_id: str) -> None:
        """A worker's executor thread entered a blocking get()/wait():
        its lease resources return to the pool so the tasks it is
        waiting ON can schedule — without this, dependent tasks each
        get()ing their dep deadlock the moment tasks outnumber CPUs
        (reference: raylet releases resources of workers blocked in
        ray.get, node_manager.cc HandleWorkerBlocked)."""
        with self._cv:
            w = self._workers.get(worker_id)
            if w is None or w.state != "BUSY" or not w.resources \
                    or w.blocked_resources:
                return
            self._release_resources(self._lease_release_node(w),
                                    w.resources)
            w.blocked_resources = w.resources
            w.resources = {}
            self._notify_all_locked()

    def worker_unblocked(self, worker_id: str) -> None:
        """Re-take the parked lease on wake. Transient oversubscription
        is allowed (availability may go negative, stalling new leases
        until it recovers) — the reference's resume semantics."""
        with self._cv:
            w = self._workers.get(worker_id)
            if w is None or not w.blocked_resources or w.state != "BUSY":
                return
            node = self._lease_release_node(w)
            if node is not None:
                for k, v in w.blocked_resources.items():
                    node.available[k] = node.available.get(k, 0.0) - v
            w.resources = w.blocked_resources
            w.blocked_resources = None
            self._notify_all_locked()

    def prestart_workers(self, n: int) -> None:
        with self._cv:
            for _ in range(n):
                self._spawn_worker()

    def list_workers(self) -> List[Dict[str, Any]]:
        with self._lock:
            # lease_node_id: the node whose resources (possibly zero —
            # 0-CPU actor leases) the current lease took; the node
            # autoscaler's idle check needs it because zero-resource
            # leases don't show up in available-vs-total accounting
            return [{"worker_id": w.worker_id, "state": w.state, "pid": w.pid,
                     "address": w.address, "node_id": w.node_id,
                     "lease_node_id": w.lease_node_id}
                    for w in self._workers.values()]

    # ----------------------------------------------------------------- actors

    def create_actor(self, spec_bytes: bytes, name: Optional[str],
                     namespace: str, resources: Dict[str, float],
                     max_restarts: int, max_task_retries: int,
                     placement_group_id: Optional[str] = None,
                     get_if_exists: bool = False,
                     scheduling_strategy: Any = "DEFAULT") -> Dict[str, Any]:
        """GCS-mediated actor creation (reference gcs_actor_manager.cc:255,280)."""
        with self._cv:
            if name is not None:
                existing = self._named_actors.get((namespace, name))
                if existing is not None:
                    rec = self._actors[existing]
                    if rec.state != "DEAD":
                        if get_if_exists:
                            return self._actor_info_locked(rec)
                        raise ValueError(
                            f"actor name {name!r} already taken in namespace "
                            f"{namespace!r}")
            actor_id = ActorID().hex()
            rec = ActorRecord(actor_id=actor_id, name=name, namespace=namespace,
                              spec=spec_bytes,
                              restarts_remaining=max_restarts,
                              max_task_retries=max_task_retries,
                              resources=dict(resources or {}),
                              placement_group_id=placement_group_id,
                              scheduling_strategy=scheduling_strategy)
            self._actors[actor_id] = rec
            self._dirty = True
            if name is not None:
                self._named_actors[(namespace, name)] = actor_id
        self._place_actor(actor_id)
        with self._lock:
            return self._actor_info_locked(self._actors[actor_id])

    def _place_actor(self, actor_id: str) -> None:
        """Lease a dedicated worker and instantiate the actor on it."""
        with self._lock:
            rec = self._actors[actor_id]
            spec, res, pg = rec.spec, rec.resources, rec.placement_group_id
            # getattr: records restored from a pre-upgrade snapshot were
            # pickled without the field (pickle bypasses dataclass defaults)
            strat = getattr(rec, "scheduling_strategy", "DEFAULT")
        try:
            worker_id, address = self.lease_worker(
                res, placement_group_id=pg, strategy=strat)
        except (TimeoutError, RuntimeError, exc.SchedulingError) as e:
            with self._cv:
                rec.state = "DEAD"
                rec.death_cause = f"scheduling failed: {e}"
                self._notify_all_locked()
            return
        client = self._clients.get(address)
        try:
            client.call("become_actor", actor_id, spec,
                        timeout=_worker_start_timeout())
        except Exception as e:  # creation failed on the worker
            self.return_worker(worker_id)
            with self._cv:
                rec.state = "DEAD"
                rec.death_cause = f"__init__ failed: {e}"
                self._notify_all_locked()
            return
        with self._cv:
            w = self._workers.get(worker_id)
            if w is not None:
                w.state = "ACTOR"
            rec.worker_id = worker_id
            rec.address = address
            rec.state = "ALIVE"
            self._dirty = True
            self._notify_all_locked()
        self.publish("actor_state", {"actor_id": actor_id, "state": "ALIVE"})

    def get_actor_info(self, actor_id: Optional[str] = None,
                       name: Optional[str] = None,
                       namespace: str = "default",
                       wait_alive_timeout: float = 0.0) -> Dict[str, Any]:
        deadline = time.monotonic() + wait_alive_timeout
        with self._cv:
            while True:
                if actor_id is None:
                    aid = self._named_actors.get((namespace, name))
                    if aid is None:
                        raise ValueError(
                            f"no actor named {name!r} in namespace {namespace!r}")
                else:
                    aid = actor_id
                rec = self._actors.get(aid)
                if rec is None:
                    raise ValueError(f"unknown actor {aid}")
                if rec.state == "ALIVE" or rec.state == "DEAD" \
                        or time.monotonic() >= deadline:
                    return self._actor_info_locked(rec)
                self._cv.wait(min(0.1, max(0.0, deadline - time.monotonic())))

    def _actor_info_locked(self, rec: ActorRecord) -> Dict[str, Any]:
        return {"actor_id": rec.actor_id, "state": rec.state,
                "address": rec.address, "name": rec.name,
                "namespace": rec.namespace, "death_cause": rec.death_cause,
                "max_task_retries": rec.max_task_retries,
                "num_restarts": rec.num_restarts}

    def list_actors(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [self._actor_info_locked(r) for r in self._actors.values()]

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        with self._cv:
            rec = self._actors.get(actor_id)
            if rec is None:
                return
            if no_restart:
                rec.restarts_remaining = 0
            worker_id = rec.worker_id
            w = self._workers.get(worker_id) if worker_id else None
            if w is not None and w.state != "DEAD" and \
                    not (w.proc is not None and w.proc.poll() is not None):
                # deliberate kill of a LIVE worker: don't charge the
                # failure tracker. A worker that already exited on its
                # own (crash racing this kill — e.g. a gang teardown
                # sweeping over the rank whose death triggered it) died
                # organically and must still count toward quarantine.
                w.expected_death = True
        if w is not None and w.proc is not None:
            try:
                w.proc.kill()
            except OSError:
                pass
        elif w is not None and w.pid is not None:
            try:
                os.kill(w.pid, 9)
            except OSError:
                pass
        # monitor loop will observe the death and finalize state

    def report_actor_exit(self, actor_id: str, cause: str) -> None:
        """Graceful exit (__ray_terminate__)."""
        with self._cv:
            rec = self._actors.get(actor_id)
            if rec is None:
                return
            rec.state = "DEAD"
            rec.death_cause = cause
            rec.restarts_remaining = 0
            self._dirty = True
            if rec.worker_id:
                w = self._workers.get(rec.worker_id)
                if w is not None and w.state == "ACTOR":
                    w.state = "DEAD"
                    # monitor skips DEAD workers, so release the lease here
                    self._release_resources(self._lease_release_node(w),
                                            w.resources)
                    w.resources = {}
                    self._free_worker_chips(w)
            self._notify_all_locked()
        self.publish("actor_state", {"actor_id": actor_id, "state": "DEAD"})

    # ------------------------------------------------------------------- KV

    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True,
               namespace: str = "default") -> bool:
        with self._lock:
            ns = self._kv.setdefault(namespace, {})
            if not overwrite and key in ns:
                return False
            ns[key] = value
            self._dirty = True
            return True

    def kv_get(self, key: bytes, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self._kv.get(namespace, {}).get(key)

    def kv_del(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            self._dirty = True
            return self._kv.get(namespace, {}).pop(key, None) is not None

    def kv_keys(self, prefix: bytes = b"", namespace: str = "default") -> List[bytes]:
        with self._lock:
            return [k for k in self._kv.get(namespace, {}) if k.startswith(prefix)]

    # ---------------------------------------------------------------- pubsub

    def subscribe(self, channel: str, address: Tuple[str, int]) -> None:
        with self._lock:
            subs = self._subs.setdefault(channel, [])
            if tuple(address) not in subs:
                subs.append(tuple(address))

    def publish(self, channel: str, message: Any) -> None:
        if channel == "worker_logs":
            # ring buffer for the dashboard's log viewer (reference: the
            # dashboard's log tailing endpoints)
            buf = getattr(self, "_recent_logs", None)
            if buf is None:
                import collections

                buf = self._recent_logs = collections.deque(maxlen=2000)
            ts = time.time()
            for entry in (message if isinstance(message, list) else ()):
                buf.append({**entry, "ts": ts})
        with self._lock:
            subs = list(self._subs.get(channel, []))
        for addr in subs:
            try:
                self._clients.get(addr).notify("on_published", channel, message)
            except Exception:
                pass

    def get_recent_logs(self, limit: int = 500) -> List[Dict[str, Any]]:
        buf = getattr(self, "_recent_logs", None)
        if not buf:
            return []
        return list(buf)[-limit:]

    # ------------------------------------------------------- placement groups

    def _assign_bundles(self, bundles: List[Dict[str, float]],
                        strategy: str) -> List[str]:
        """Pick a node per bundle (reference composite/bundle scheduling
        policies, scheduling/policy/bundle_scheduling_policy.h):
        PACK = first-fit onto the fewest nodes, SPREAD = round-robin with
        overflow, STRICT_PACK = one node or fail, STRICT_SPREAD =
        distinct nodes or fail. Must hold the lock. Raises ValueError
        when infeasible; mutates nothing."""
        order = [self._head_node_id] + sorted(
            nid for nid, n in self._nodes.items()
            if nid != self._head_node_id and n.alive)
        # quarantined/draining hosts are excluded from gang formation;
        # an all-excluded cluster falls back to the full list (liveness)
        kept = [nid for nid in order
                if not self._fd_tracker.is_excluded(nid)]
        if kept:
            order = kept
        avail = {nid: dict(self._nodes[nid].available) for nid in order}

        def fits(nid, b):
            return all(avail[nid].get(k, 0.0) >= v for k, v in b.items())

        def take(nid, b):
            for k, v in b.items():
                avail[nid][k] = avail[nid].get(k, 0.0) - v

        if strategy == "STRICT_PACK":
            for nid in order:
                trial = dict(avail[nid])
                ok = True
                for b in bundles:
                    if all(trial.get(k, 0.0) >= v for k, v in b.items()):
                        for k, v in b.items():
                            trial[k] = trial.get(k, 0.0) - v
                    else:
                        ok = False
                        break
                if ok:
                    return [nid] * len(bundles)
            raise ValueError(
                "STRICT_PACK infeasible: no single node fits all bundles")

        if strategy == "STRICT_SPREAD":
            assignment: List[str] = []
            used: set = set()
            for b in bundles:
                placed = next((nid for nid in order
                               if nid not in used and fits(nid, b)), None)
                if placed is None:
                    raise ValueError(
                        "STRICT_SPREAD infeasible: needs "
                        f"{len(bundles)} distinct nodes with capacity, "
                        f"have {len(order)}")
                take(placed, b)
                used.add(placed)
                assignment.append(placed)
            return assignment

        if strategy == "SPREAD":
            assignment = []
            start = 0
            for b in bundles:
                rotation = order[start:] + order[:start]
                placed = next((nid for nid in rotation if fits(nid, b)),
                              None)
                if placed is None:
                    raise ValueError(
                        f"SPREAD infeasible: no node fits bundle {b}")
                take(placed, b)
                assignment.append(placed)
                start = (order.index(placed) + 1) % len(order)
            return assignment

        # PACK: first-fit in fixed order keeps bundles on the fewest nodes
        assignment = []
        for b in bundles:
            placed = next((nid for nid in order if fits(nid, b)), None)
            if placed is None:
                raise ValueError(f"PACK infeasible: no node fits bundle {b}")
            take(placed, b)
            assignment.append(placed)
        return assignment

    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str = "PACK",
                               name: Optional[str] = None) -> str:
        """Assign each bundle to a node per the strategy, then reserve
        atomically with rollback on partial failure (reference 2PC
        gcs_placement_group_scheduler.cc — single authority here, so the
        transaction is a lock-held reserve loop)."""
        pg_id = PlacementGroupID().hex()
        with self._cv:
            assignment = self._assign_bundles(bundles, strategy)
            reserved: List[Tuple[NodeRecord, Dict[str, float]]] = []
            ok = True
            for b, nid in zip(bundles, assignment):
                node = self._nodes[nid]
                if not self._acquire_resources(node, b):
                    ok = False
                    break
                reserved.append((node, b))
            if not ok:  # raced with a concurrent reservation: roll back
                for node, b in reserved:
                    self._release_resources(node, b)
                raise ValueError(
                    f"placement group infeasible: bundles {bundles} "
                    "no longer fit their assigned nodes")
            # expose per-PG bundle pools as synthetic resources ON THE
            # ASSIGNED NODES — leases carrying the _pg_ prefix can then
            # only be satisfied where the bundle actually lives
            for b, nid in zip(bundles, assignment):
                node = self._nodes[nid]
                for k, v in b.items():
                    pk = f"_pg_{pg_id}_{k}"
                    node.total[pk] = node.total.get(pk, 0) + v
                    node.available[pk] = node.available.get(pk, 0) + v
            self._pgs[pg_id] = PlacementGroupRecord(
                pg_id=pg_id, bundles=bundles, strategy=strategy, name=name,
                assignments=assignment)
            self._dirty = True
            self._notify_all_locked()
        return pg_id

    def placement_group_ready(self, pg_id: str) -> bool:
        with self._lock:
            pg = self._pgs.get(pg_id)
            return pg is not None and pg.state == "CREATED"

    def remove_placement_group(self, pg_id: str) -> None:
        with self._cv:
            pg = self._pgs.pop(pg_id, None)
            if pg is None:
                return
            assignments = pg.assignments or \
                [self._head_node_id] * len(pg.bundles)
            for b, nid in zip(pg.bundles, assignments):
                node = self._nodes.get(nid)
                if node is None:  # node died: its capacity died with it
                    continue
                for k in b:
                    pk = f"_pg_{pg_id}_{k}"
                    node.total.pop(pk, None)
                    node.available.pop(pk, None)
                self._release_resources(node, b)
            self._dirty = True
            self._notify_all_locked()

    def list_placement_groups(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"pg_id": p.pg_id, "bundles": p.bundles,
                     "strategy": p.strategy, "state": p.state,
                     "name": p.name, "assignments": list(p.assignments)}
                    for p in self._pgs.values()]

    # ------------------------------------------------------------ task events

    def report_task_events(self, events: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._task_events.extend(events)
            if len(self._task_events) > 100_000:
                del self._task_events[:len(self._task_events) - 100_000]

    def report_spans(self, spans: List[Dict[str, Any]]) -> None:
        """Tracing spans flushed by workers/drivers (reference: GCS task-
        event store aggregating OTel-style spans; util/tracing.py drain)."""
        with self._lock:
            self._spans.extend(spans)
            if len(self._spans) > 100_000:
                del self._spans[:len(self._spans) - 100_000]

    def get_spans(self, limit: int = 10_000) -> List[Dict[str, Any]]:
        with self._lock:
            return self._spans[-limit:]

    def get_task_events(self, limit: int = 10_000) -> List[Dict[str, Any]]:
        with self._lock:
            return self._task_events[-limit:]

    # ------------------------------------------------------ flight recorder
    # Gang-wide step telemetry (ray_tpu.observability): every rank's
    # StepTimer ships per-step records here; the per-run ring buffer is
    # the source for straggler detection (util.state.train_progress),
    # the dashboard /api/train route, and `ray_tpu train-status`.

    _TRAIN_STEPS_KEPT = 1024   # per-run step window
    _TRAIN_RUNS_KEPT = 16      # oldest runs evicted past this

    def report_train_steps(self, run_id: str, rank: int,
                           records: List[Dict[str, Any]]) -> None:
        with self._lock:
            run = self._train_runs.setdefault(
                run_id, {"steps": {}, "updated": 0.0})
            steps = run["steps"]
            for rec in records:
                step = int(rec.get("step", 0))
                steps.setdefault(step, {})[int(rank)] = rec
            if len(steps) > self._TRAIN_STEPS_KEPT:
                for s in sorted(steps)[:len(steps)
                                       - self._TRAIN_STEPS_KEPT]:
                    del steps[s]
            run["updated"] = time.time()
            if len(self._train_runs) > self._TRAIN_RUNS_KEPT:
                oldest = sorted(self._train_runs,
                                key=lambda r:
                                self._train_runs[r]["updated"])
                for r in oldest[:len(self._train_runs)
                                - self._TRAIN_RUNS_KEPT]:
                    del self._train_runs[r]

    def get_train_progress(self) -> Dict[str, Any]:
        """Per-run gang summaries (per-rank stats, skew, stragglers) —
        aggregation math lives in ray_tpu.observability.gang. Step
        records are write-once (inserted/replaced, never mutated), so a
        two-level shallow copy isolates the summarizer without paying a
        deep copy of up to 16k records inside the conductor lock."""
        from ray_tpu.observability import gang

        with self._lock:
            snapshot = {
                run_id: {s: dict(by_rank)
                         for s, by_rank in run["steps"].items()}
                for run_id, run in self._train_runs.items()}
        return {run_id: gang.summarize_run(steps)
                for run_id, steps in snapshot.items()}

    def get_train_steps(self, limit: int = 10_000) -> List[Dict[str, Any]]:
        """Raw step records, flattened newest-last with run_id attached —
        the merged-timeline source (observability.timeline). Only a
        two-level shallow snapshot happens under the lock (records are
        write-once, see get_train_progress); the flatten/sort over
        potentially ~1M records runs outside it."""
        with self._lock:
            snapshot = {
                run_id: {s: dict(by_rank)
                         for s, by_rank in run["steps"].items()}
                for run_id, run in self._train_runs.items()}
        out: List[Dict[str, Any]] = []
        for run_id, steps in snapshot.items():
            for step in sorted(steps):
                for rank, rec in sorted(steps[step].items()):
                    out.append(dict(rec, run_id=run_id, rank=rank))
        out.sort(key=lambda r: r.get("t_start") or 0.0)
        return out[-limit:]

    # --------------------------------------------------------- resilience
    # ray_tpu.resilience: the conductor is the authority for preemption
    # broadcast, failure-domain quarantine, and the resilience event log
    # (restart/preemption/quarantine markers for the merged timeline).

    _RESILIENCE_EVENTS_KEPT = 10_000

    def _resilience_record_locked(self, event: Dict[str, Any]) -> None:
        """Append an event + bump its kind counter. Must hold the lock."""
        event.setdefault("ts", time.time())
        self._resilience_events.append(event)
        if len(self._resilience_events) > self._RESILIENCE_EVENTS_KEPT:
            del self._resilience_events[
                :len(self._resilience_events)
                - self._RESILIENCE_EVENTS_KEPT]
        kind = str(event.get("kind", "other"))
        self._resilience_counters[kind] = \
            self._resilience_counters.get(kind, 0) + 1
        if kind == "recovery" and event.get("ttr_s") is not None:
            self._last_ttr_s = float(event["ttr_s"])
        try:
            counter, ttr = _resilience_metrics()
            counter.inc(tags={"kind": kind})
            if kind == "recovery" and self._last_ttr_s is not None:
                ttr.set(self._last_ttr_s)
        except Exception:  # noqa: BLE001 — metrics must never fail an
            pass           # event report

    def _record_failure(self, node_id: str, kind: str, detail: str = "",
                        worker_id: Optional[str] = None) -> None:
        """Charge `node_id`'s failure domain; emits a quarantine event
        on the not-quarantined -> quarantined transition."""
        was = self._fd_tracker.is_quarantined(node_id)
        score = self._fd_tracker.record(node_id, kind, detail=detail)
        with self._lock:
            self._resilience_record_locked(
                {"kind": kind, "node_id": node_id, "detail": detail,
                 "worker_id": worker_id, "score": round(score, 4)})
            if not was and self._fd_tracker.is_quarantined(node_id):
                self._resilience_record_locked(
                    {"kind": "quarantine", "node_id": node_id,
                     "detail": f"score {score:.2f} >= threshold "
                               f"{self._fd_tracker.threshold:g}"})

    def report_preemption(self, node_id: Optional[str] = None,
                          worker_id: Optional[str] = None,
                          grace_s: Optional[float] = None,
                          reason: str = "maintenance") -> Dict[str, Any]:
        """A host announced it is going away (maintenance event, spot
        reclaim, SIGTERM). Starts draining the host — no new leases or
        bundles land on it for the grace window — and broadcasts
        "checkpoint now, grace N seconds" on the `resilience` pubsub
        channel, where training sessions pick it up
        (ray_tpu.train.preemption_requested)."""
        from .config import config

        grace = config.preempt_grace_s if grace_s is None else \
            float(grace_s)
        with self._cv:
            if node_id is None and worker_id is not None:
                w = self._workers.get(worker_id)
                if w is not None:
                    node_id = w.lease_node_id or w.node_id
            if node_id is None:
                node_id = self._head_node_id
            self._fd_tracker.begin_drain(
                node_id, time.monotonic() + grace, reason)
            event = {"kind": "preemption", "ts": time.time(),
                     "node_id": node_id, "grace_s": grace,
                     "deadline": time.time() + grace, "reason": reason}
            self._resilience_record_locked(event)
            self._notify_all_locked()
        self.publish("resilience", event)
        return event

    def report_resilience_event(self, event: Dict[str, Any]) -> None:
        """Generic event sink for trainers/supervisors/chaos: restart,
        grace_checkpoint, gang_peer_death, elastic_reform, recovery
        (with time-to-recovery `ttr_s`), chaos injections."""
        if not isinstance(event, dict):
            return
        with self._lock:
            self._resilience_record_locked(dict(event))

    def quarantine_node(self, node_id: str, reason: str = "manual") -> None:
        """Operator pin: exclude a node until clear_quarantine."""
        self._fd_tracker.quarantine(node_id, reason)
        with self._cv:
            self._resilience_record_locked(
                {"kind": "quarantine", "node_id": node_id,
                 "detail": reason, "manual": True})
            self._notify_all_locked()

    def clear_quarantine(self, node_id: str) -> bool:
        cleared = self._fd_tracker.clear(node_id)
        with self._cv:
            if cleared:
                self._resilience_record_locked(
                    {"kind": "quarantine_cleared", "node_id": node_id})
            self._notify_all_locked()
        return cleared

    def get_resilience_status(self) -> Dict[str, Any]:
        """State-API/dashboard view: per-domain scores + quarantine/
        drain flags, excluded hosts, counters, recent events."""
        status = self._fd_tracker.status()
        with self._lock:
            return {
                "domains": status["domains"],
                "threshold": status["threshold"],
                "half_life_s": status["half_life_s"],
                "excluded": self._fd_tracker.excluded(),
                "head_node_id": self._head_node_id,
                "counters": dict(self._resilience_counters),
                "last_ttr_s": self._last_ttr_s,
                "recent_events": self._resilience_events[-50:],
            }

    def get_resilience_events(self, limit: int = 10_000
                              ) -> List[Dict[str, Any]]:
        """Raw event log, oldest first — the merged-timeline source."""
        with self._lock:
            return self._resilience_events[-limit:]

    def schedulable_resources(self) -> Dict[str, float]:
        """available_resources minus quarantined/draining hosts — what a
        gang re-form can actually get (elastic sizing input)."""
        with self._lock:
            # copy under the lock: other RPCs insert/pop _pg_ keys in
            # these dicts, and iterating them unlocked can raise
            # "dictionary changed size during iteration"
            nodes = [(n.node_id, dict(n.available))
                     for n in self._nodes.values() if n.alive]
        out: Dict[str, float] = {}
        for node_id, available in nodes:
            if self._fd_tracker.is_excluded(node_id):
                continue
            for k, v in available.items():
                out[k] = out.get(k, 0) + v
        return out

    # ------------------------------------------------------ weight fabric
    # ray_tpu.weights: the conductor is the version registry. Producers
    # publish their LOCAL shards into their own object stores and send
    # only a metadata fragment here; the version commits atomically when
    # every host's fragment is in. Keep-last-K GC and partial-publish
    # reaping notify producers over the `weights` pubsub channel so they
    # can free the dropped chunks they own.

    _WEIGHT_EVENTS_KEPT = 10_000

    def _weight_event_locked(self, event: Dict[str, Any]) -> None:
        event.setdefault("ts", time.time())
        self._weight_events.append(event)
        if len(self._weight_events) > self._WEIGHT_EVENTS_KEPT:
            del self._weight_events[
                :len(self._weight_events) - self._WEIGHT_EVENTS_KEPT]

    def report_weight_event(self, event: Dict[str, Any]) -> None:
        """Client-side markers (fetch, swap) for the merged timeline —
        publish/gc/reap events are recorded by the registry itself."""
        if not isinstance(event, dict):
            return
        with self._lock:
            self._weight_event_locked(dict(event))

    def get_weight_events(self, limit: int = 10_000) -> List[Dict[str, Any]]:
        with self._lock:
            return self._weight_events[-limit:]

    # ------------------------------------------------- paged KV cache
    # Serving engines (models/engine.py) push their prefix-cache stat
    # snapshots and instant markers here; util.state.kv_cache_stats(),
    # `ray_tpu kvcache`, and the dashboard /api/kvcache all read the
    # same aggregate so every surface reports one set of numbers.

    _KVCACHE_EVENTS_KEPT = 10_000
    _KVCACHE_TOTAL_KEYS = (
        "lookups", "hits", "partial_hits", "misses", "reused_tokens",
        "prefilled_tokens", "spliced_tokens", "inserted_blocks",
        "evictions", "cow_copies", "invalidations", "admitted",
        "prefill_admitted", "adopted", "prefill_calls",
        "spec_proposed", "spec_accepted", "spec_verify_ticks",
        "spec_emitted_tokens")

    def report_kvcache_stats(self, worker_id: str, engine_id: str,
                             stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        key = f"{str(worker_id)[:12]}:{engine_id}"
        with self._lock:
            self._kvcache_stats[key] = dict(
                stats, worker_id=worker_id, engine_id=engine_id,
                ts=time.time())

    def get_kvcache_stats(self) -> Dict[str, Any]:
        with self._lock:
            engines = {k: dict(v) for k, v in self._kvcache_stats.items()}
        totals: Dict[str, Any] = {k: 0 for k in self._KVCACHE_TOTAL_KEYS}
        for st in engines.values():
            for k in self._KVCACHE_TOTAL_KEYS:
                v = st.get(k)
                if isinstance(v, (int, float)):
                    totals[k] += v
        looked = totals["lookups"]
        totals["hit_rate"] = ((totals["hits"] + totals["partial_hits"])
                              / looked if looked else 0.0)
        seen = totals["reused_tokens"] + totals["prefilled_tokens"]
        totals["token_reuse_rate"] = (totals["reused_tokens"] / seen
                                      if seen else 0.0)
        return {"engines": engines, "totals": totals}

    def get_speculation_stats(self) -> Dict[str, Any]:
        """The speculative-decoding slice of the kvcache snapshots
        (engines embed their spec counters in the same kv_stats push —
        ONE report channel, so util.state.speculation_stats(),
        `ray_tpu speculate`, /api/speculation, and Prometheus can never
        disagree with the kvcache surface). Engines that never enabled
        speculation are filtered out of `engines` but an all-zero
        totals dict is still returned."""
        with self._lock:
            snaps = {k: dict(v) for k, v in self._kvcache_stats.items()}
        engines = {k: {
            "engine_id": v.get("engine_id"),
            "speculate_k": v.get("speculate_k", 0),
            "spec_proposed": v.get("spec_proposed", 0),
            "spec_accepted": v.get("spec_accepted", 0),
            "spec_verify_ticks": v.get("spec_verify_ticks", 0),
            "spec_emitted_tokens": v.get("spec_emitted_tokens", 0),
            "acceptance_rate": v.get("acceptance_rate", 0.0),
            "tokens_per_verify": v.get("tokens_per_verify", 0.0),
            "kv_int8": v.get("kv_int8", False),
            "ts": v.get("ts"),
        } for k, v in snaps.items() if v.get("speculate_k")}
        from ray_tpu.util.state import speculation_totals

        return {"engines": engines,
                "totals": speculation_totals(engines)}

    def report_kvcache_event(self, event: Dict[str, Any]) -> None:
        """Prefix-hit / evict / invalidate instant markers for the
        merged timeline (observability.timeline)."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._kvcache_events.append(event)
            if len(self._kvcache_events) > self._KVCACHE_EVENTS_KEPT:
                del self._kvcache_events[
                    :len(self._kvcache_events)
                    - self._KVCACHE_EVENTS_KEPT]

    def get_kvcache_events(self, limit: int = 10_000
                           ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._kvcache_events[-limit:]

    # --------------------------------------------- online learning loop
    # Samplers / the rollout buffer / the learner (ray_tpu.online) push
    # their stat snapshots and instant markers here; util.state
    # .online_status(), `ray_tpu online`, and the dashboard /api/online
    # all read the same aggregate so every surface reports one set of
    # numbers.

    _ONLINE_EVENTS_KEPT = 10_000
    _ONLINE_STATS_KEPT = 256

    def report_online_stats(self, worker_id: str, component_id: str,
                            stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._online_stats[str(component_id)] = dict(
                stats, worker_id=worker_id,
                component_id=str(component_id), ts=time.time())
            # learner snapshots are keyed by unique run ids: without an
            # eviction bound, every finished run's final snapshot would
            # accumulate forever. Oldest-first by last report time.
            while len(self._online_stats) > self._ONLINE_STATS_KEPT:
                oldest = min(self._online_stats,
                             key=lambda k:
                             self._online_stats[k].get("ts", 0.0))
                del self._online_stats[oldest]

    def get_online_status(self) -> Dict[str, Any]:
        """One aggregate for every online-loop surface: components
        grouped by role (sampler / buffer / learner) plus cluster
        totals (rollouts, rollout tokens, buffer occupancy, learner
        ingest, worst sampler staleness)."""
        with self._lock:
            comps = {k: dict(v) for k, v in self._online_stats.items()}
        samplers = {k: v for k, v in comps.items()
                    if v.get("role") == "sampler"}
        buffers = {k: v for k, v in comps.items()
                   if v.get("role") == "buffer"}
        learners = {k: v for k, v in comps.items()
                    if v.get("role") == "learner"}
        totals: Dict[str, Any] = {
            "samplers": len(samplers),
            "rollouts": sum(int(s.get("rollouts", 0))
                            for s in samplers.values()),
            "rollout_tokens": sum(int(s.get("rollout_tokens", 0))
                                  for s in samplers.values()),
            "swaps": sum(int(s.get("swap_count", 0))
                         for s in samplers.values()),
            "buffer_occupancy": sum(int(b.get("occupancy", 0))
                                    for b in buffers.values()),
            "buffer_capacity": sum(int(b.get("capacity", 0))
                                   for b in buffers.values()),
            "buffer_rejected": sum(int(b.get("rejected", 0))
                                   for b in buffers.values()),
            "ingested_rollouts": sum(int(l.get("ingested_rollouts", 0))
                                     for l in learners.values()),
            "ingested_tokens": sum(int(l.get("ingested_tokens", 0))
                                   for l in learners.values()),
            "learner_steps": max((int(l.get("steps", 0))
                                  for l in learners.values()),
                                 default=0),
            "published_versions": max((int(l.get("published_version", 0))
                                       for l in learners.values()),
                                      default=0),
        }
        stale = [s.get("staleness_versions") for s in samplers.values()
                 if s.get("staleness_versions") is not None]
        totals["staleness_versions"] = max(stale) if stale else None
        high = [s.get("max_staleness_versions")
                for s in samplers.values()
                if s.get("max_staleness_versions") is not None]
        totals["max_staleness_versions"] = max(high + stale) \
            if (high or stale) else None
        return {"samplers": samplers, "buffers": buffers,
                "learners": learners, "totals": totals}

    def report_online_event(self, event: Dict[str, Any]) -> None:
        """Rollout / publish / swap / ingest instant markers for the
        merged timeline's online lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._online_events.append(event)
            if len(self._online_events) > self._ONLINE_EVENTS_KEPT:
                del self._online_events[
                    :len(self._online_events)
                    - self._ONLINE_EVENTS_KEPT]

    def get_online_events(self, limit: int = 10_000
                          ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._online_events[-limit:]

    # ---------------------------------------------- disaggregated serving
    # Prefill/decode servers and routers (serve/disagg.py) push their
    # stat snapshots and instant markers here; util.state.disagg_status(),
    # `ray_tpu disagg`, and the dashboard /api/disagg all read the same
    # aggregate so every surface reports one set of numbers.

    _DISAGG_EVENTS_KEPT = 10_000
    _DISAGG_STATS_KEPT = 256
    # live gauges (router queue depth) only count snapshots at most this
    # old — routers re-push on every dispatch/complete (0.5s throttle),
    # so anything older is a dead component's frozen last word
    _DISAGG_GAUGE_FRESH_S = 15.0

    def report_disagg_stats(self, worker_id: str, component_id: str,
                            stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._disagg_stats[str(component_id)] = dict(
                stats, worker_id=worker_id,
                component_id=str(component_id), ts=time.time())
            while len(self._disagg_stats) > self._DISAGG_STATS_KEPT:
                oldest = min(self._disagg_stats,
                             key=lambda k:
                             self._disagg_stats[k].get("ts", 0.0))
                del self._disagg_stats[oldest]

    def get_disagg_status(self) -> Dict[str, Any]:
        """One aggregate for every disagg surface: components grouped
        by role (prefill / decode / router) plus cluster totals
        (transfers, KV bytes split shm/rpc, adoptions, sheds, live
        queue depth)."""
        with self._lock:
            comps = {k: dict(v) for k, v in self._disagg_stats.items()}
        now = time.time()
        prefill = {k: v for k, v in comps.items()
                   if v.get("role") == "prefill"}
        decode = {k: v for k, v in comps.items()
                  if v.get("role") == "decode"}
        routers = {k: v for k, v in comps.items()
                   if v.get("role") == "router"}
        totals: Dict[str, Any] = {
            "prefill_replicas": len(prefill),
            "decode_replicas": len(decode),
            "prefills": sum(int(p.get("prefills", 0))
                            for p in prefill.values()),
            "prefilled_tokens": sum(int(p.get("prefilled_tokens", 0))
                                    for p in prefill.values()),
            "reused_tokens": sum(int(p.get("reused_tokens", 0))
                                 for p in prefill.values()),
            "published_transfers": sum(
                int(p.get("published_transfers", 0))
                for p in prefill.values()),
            "published_bytes": sum(int(p.get("published_bytes", 0))
                                   for p in prefill.values()),
            "transfers": sum(int(d.get("transfers", 0))
                             for d in decode.values()),
            "kv_fetched_bytes": sum(int(d.get("kv_fetched_bytes", 0))
                                    for d in decode.values()),
            "shm_bytes": sum(int(d.get("shm_bytes", 0))
                             for d in decode.values()),
            "rpc_bytes": sum(int(d.get("rpc_bytes", 0))
                             for d in decode.values()),
            "adopted": sum(int(d.get("adopted", 0))
                           for d in decode.values()),
            "decoded_tokens": sum(int(d.get("decoded_tokens", 0))
                                  for d in decode.values()),
            "dispatched": sum(int(r.get("dispatched", 0))
                              for r in routers.values()),
            "shed": sum(int(r.get("shed", 0))
                        for r in routers.values()),
            # live gauge, not a counter: a crashed router's final
            # snapshot (which never expires from the roster) must not
            # contribute phantom queue depth forever — only snapshots
            # fresh enough to still describe a living component count.
            # Monotonic counters above tolerate stale snapshots; this
            # is the input signal for the planned SLO autoscaler.
            "queue_depth": sum(
                int(r.get("pending", 0)) for r in routers.values()
                if now - float(r.get("ts", 0.0))
                <= self._DISAGG_GAUGE_FRESH_S),
            "max_queue_depth_seen": max(
                (int(r.get("max_pending", 0))
                 for r in routers.values()), default=0),
        }
        return {"prefill": prefill, "decode": decode,
                "routers": routers, "totals": totals}

    def report_disagg_event(self, event: Dict[str, Any]) -> None:
        """kv_publish / kv_transfer / shed instant markers for the
        merged timeline's disagg lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._disagg_events.append(event)
            if len(self._disagg_events) > self._DISAGG_EVENTS_KEPT:
                del self._disagg_events[
                    :len(self._disagg_events)
                    - self._DISAGG_EVENTS_KEPT]

    def get_disagg_events(self, limit: int = 10_000
                          ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._disagg_events[-limit:]

    # ------------------------------------------------- global KV plane
    # Replicas (serve/kvplane.py HostArena owners, routers) push tier-2
    # arena / tier-3 adoption snapshots and spill/adopt/directory
    # markers here, and the cluster-wide PREFIX DIRECTORY lives here:
    # (namespace, digest-chain) -> holder + chunk descriptor — metadata
    # only, the weight-fabric registry pattern (atomic commit, TTL
    # reap, keep-last-K GC). util.state.kvplane_status(), `ray_tpu
    # kvplane`, and the dashboard /api/kvplane all read the same
    # aggregate so every surface reports one set of numbers.

    _KVPLANE_EVENTS_KEPT = 10_000
    _KVPLANE_STATS_KEPT = 256
    _KVPLANE_DIR_KEPT = 4096
    _KVPLANE_GAUGE_FRESH_S = 15.0
    _KVPLANE_TOTAL_KEYS = (
        "spills", "spill_bytes", "tier2_hits", "tier2_probes",
        "tier2_reused_tokens", "tier2_fetched_bytes",
        "arena_evictions", "tier3_publishes", "tier3_adopts",
        "tier3_adopted_blocks", "tier3_reused_tokens",
        "tier3_fetched_bytes", "directory_hits", "directory_misses",
        "directory_fallbacks")

    def report_kvplane_stats(self, worker_id: str, component_id: str,
                             stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._kvplane_stats[str(component_id)] = dict(
                stats, worker_id=worker_id,
                component_id=str(component_id), ts=time.time())
            while len(self._kvplane_stats) > self._KVPLANE_STATS_KEPT:
                oldest = min(self._kvplane_stats,
                             key=lambda k:
                             self._kvplane_stats[k].get("ts", 0.0))
                del self._kvplane_stats[oldest]

    def get_kvplane_stats(self) -> Dict[str, Any]:
        with self._lock:
            comps = {k: dict(v) for k, v in self._kvplane_stats.items()}
        now = time.time()
        totals: Dict[str, Any] = {k: 0 for k in self._KVPLANE_TOTAL_KEYS}
        for st in comps.values():
            for k in self._KVPLANE_TOTAL_KEYS:
                v = st.get(k)
                if isinstance(v, (int, float)):
                    totals[k] += v
        # live gauges: only snapshots fresh enough to describe a living
        # replica count (the disagg queue-depth discipline)
        totals["arena_entries"] = sum(
            int(c.get("entries", 0)) for c in comps.values()
            if now - float(c.get("ts", 0.0))
            <= self._KVPLANE_GAUGE_FRESH_S)
        totals["arena_bytes"] = sum(
            int(c.get("bytes", 0)) for c in comps.values()
            if now - float(c.get("ts", 0.0))
            <= self._KVPLANE_GAUGE_FRESH_S)
        probes = totals["tier2_probes"]
        totals["tier2_hit_rate"] = (totals["tier2_hits"] / probes
                                    if probes else 0.0)
        looks = (totals["directory_hits"]
                 + totals["directory_misses"])
        totals["directory_hit_rate"] = (totals["directory_hits"] / looks
                                        if looks else 0.0)
        return {"components": comps, "totals": totals}

    def get_kvplane_status(self) -> Dict[str, Any]:
        """One aggregate for every kvplane surface: per-component
        snapshots + cluster totals + the prefix directory's summary
        (entries, bytes, per-namespace counts, commit/reap/GC
        counters). Directory entry payloads stay out: descriptors are
        metadata, but a status call is a human surface."""
        out = self.get_kvplane_stats()
        with self._lock:
            per_ns: Dict[str, int] = {}
            total_bytes = 0
            for (ns, _d), e in self._kvplane_dir.items():
                per_ns[ns] = per_ns.get(ns, 0) + 1
                total_bytes += int(e.get("nbytes", 0))
            out["directory"] = {
                "entries": len(self._kvplane_dir),
                "nbytes": total_bytes,
                "namespaces": per_ns,
                "counters": dict(self._kvplane_dir_counters)}
        return out

    def report_kvplane_event(self, event: Dict[str, Any]) -> None:
        """spill / tier2_hit / tier3_publish / tier3_adopt /
        directory_hit instant markers for the merged timeline's kvplane
        lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._kvplane_events.append(event)
            if len(self._kvplane_events) > self._KVPLANE_EVENTS_KEPT:
                del self._kvplane_events[
                    :len(self._kvplane_events)
                    - self._KVPLANE_EVENTS_KEPT]

    def get_kvplane_events(self, limit: int = 10_000
                           ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._kvplane_events[-limit:]

    # ---- prefix directory (the weight-fabric registry pattern) ----

    def _kvplane_ttl_s(self) -> float:
        from ray_tpu.util import envknobs

        return envknobs.get_float("RAY_TPU_KVPLANE_T3_TTL_S", 600.0)

    def kvplane_publish(self, namespace: str, digest: str,
                        meta: Dict[str, Any]) -> Dict[str, Any]:
        """Atomic metadata-only commit of one published prefix: the
        entry is visible to lookups the instant it lands, or not at
        all. A digest already committed returns ``status: already`` —
        the FIRST holder keeps serving, the late publisher drops its
        refs (no torn ownership). Error dicts, never raises (the
        weights_publish_fragment contract)."""
        if not isinstance(meta, dict) or not meta.get("holder"):
            return {"error": "kvplane_publish needs a holder in meta"}
        if not digest:
            return {"error": "kvplane_publish needs a digest"}
        key = (str(namespace or ""), str(digest))
        now = time.time()
        with self._lock:
            existing = self._kvplane_dir.get(key)
            if existing is not None:
                existing["republished"] = now
                self._kvplane_dir_counters["republishes"] += 1
                return {"status": "already",
                        "holder": existing.get("holder")}
            entry = dict(meta, namespace=key[0], digest=key[1],
                         ts=now, started=time.monotonic(),
                         last_hit=None, hits=0)
            self._kvplane_dir[key] = entry
            self._kvplane_dir_counters["publishes"] += 1
            # overall bound: oldest by recency (last hit, else commit)
            # — a runaway publisher cannot grow the directory forever
            while len(self._kvplane_dir) > self._KVPLANE_DIR_KEPT:
                oldest = min(
                    self._kvplane_dir,
                    key=lambda k: (self._kvplane_dir[k].get("last_hit")
                                   or self._kvplane_dir[k]["ts"]))
                del self._kvplane_dir[oldest]
                self._kvplane_dir_counters["gced"] += 1
            ev = {"kind": "tier3_publish", "namespace": key[0],
                  "digest": key[1][:16], "holder": meta.get("holder"),
                  "tokens": meta.get("tokens"),
                  "nbytes": meta.get("nbytes"), "ts": now}
            self._kvplane_events.append(ev)
        self.publish("kvplane", {"event": "publish", "digest": key[1],
                                 "namespace": key[0],
                                 "holder": meta.get("holder")})
        return {"status": "committed"}

    def kvplane_lookup(self, namespace: str,
                       digests: List[str]) -> Optional[Dict[str, Any]]:
        """Longest registered prefix among `digests` (caller orders
        longest-first — models/kvcache.prefix_digests' order). Expired
        entries (TTL over the monotonic commit clock) are treated as
        misses and dropped lazily."""
        ns = str(namespace or "")
        ttl = self._kvplane_ttl_s()
        now_m = time.monotonic()
        with self._lock:
            self._kvplane_dir_counters["lookups"] += 1
            for d in list(digests or [])[:64]:
                key = (ns, str(d))
                e = self._kvplane_dir.get(key)
                if e is None:
                    continue
                if ttl > 0 and now_m - e.get("started", now_m) > ttl:
                    del self._kvplane_dir[key]
                    self._kvplane_dir_counters["reaped"] += 1
                    continue
                e["last_hit"] = time.time()
                e["hits"] = int(e.get("hits", 0)) + 1
                self._kvplane_dir_counters["directory_hits"] += 1
                return {k: v for k, v in e.items() if k != "started"}
            self._kvplane_dir_counters["directory_misses"] += 1
        return None

    def kvplane_unpublish(self, namespace: str, digest: str) -> bool:
        """Holder-side retraction (replica draining / arena teardown
        drops its refs — the descriptor would dangle)."""
        key = (str(namespace or ""), str(digest))
        with self._lock:
            e = self._kvplane_dir.pop(key, None)
            if e is not None:
                self._kvplane_dir_counters["unpublished"] += 1
        return e is not None

    def kvplane_reap(self, max_age_s: Optional[float] = None) -> int:
        """Drop directory entries older than `max_age_s` (default: the
        RAY_TPU_KVPLANE_T3_TTL_S knob) on the monotonic commit clock —
        a published prefix nobody re-publishes eventually stops being
        routable, bounding how stale a holder claim can get."""
        ttl = self._kvplane_ttl_s() if max_age_s is None \
            else float(max_age_s)
        now_m = time.monotonic()
        reaped = []
        with self._lock:
            for key, e in list(self._kvplane_dir.items()):
                if now_m - e.get("started", now_m) >= ttl:
                    del self._kvplane_dir[key]
                    self._kvplane_dir_counters["reaped"] += 1
                    reaped.append(key)
            if reaped:
                self._kvplane_events.append(
                    {"kind": "reap", "entries": len(reaped),
                     "ts": time.time()})
        return len(reaped)

    def kvplane_gc(self, keep: int,
                   namespace: Optional[str] = None) -> int:
        """Keep only the newest `keep` entries (by recency: last hit,
        else commit time) — per namespace, or over the whole directory
        when namespace is None. The operator keep-last-K analog of
        weights_gc."""
        keep = max(0, int(keep))
        dropped = 0
        with self._lock:
            keys = [k for k in self._kvplane_dir
                    if namespace is None or k[0] == str(namespace or "")]
            if len(keys) > keep:
                keys.sort(key=lambda k:
                          (self._kvplane_dir[k].get("last_hit")
                           or self._kvplane_dir[k]["ts"]),
                          reverse=True)
                for k in keys[keep:]:
                    del self._kvplane_dir[k]
                    self._kvplane_dir_counters["gced"] += 1
                    dropped += 1
            if dropped:
                self._kvplane_events.append(
                    {"kind": "gc", "entries": dropped,
                     "ts": time.time()})
        return dropped

    # ------------------------------------------------ HTTP front door
    # Gateway replicas (serve/gateway.py) push request counters by
    # priority class and status code plus TTFT windows; the QoS gate
    # and routers push instant markers (accept / first_byte / preempt /
    # rate_limit / disconnect) for the merged timeline's gateway lane.
    # util.state.gateway_status(), `ray_tpu gateway`, and the dashboard
    # /api/gateway all read the same aggregate.

    _GATEWAY_EVENTS_KEPT = 10_000
    _GATEWAY_STATS_KEPT = 64

    def report_gateway_stats(self, worker_id: str, component_id: str,
                             stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._gateway_stats[str(component_id)] = dict(
                stats, worker_id=worker_id,
                component_id=str(component_id), ts=time.time())
            while len(self._gateway_stats) > self._GATEWAY_STATS_KEPT:
                oldest = min(self._gateway_stats,
                             key=lambda k:
                             self._gateway_stats[k].get("ts", 0.0))
                del self._gateway_stats[oldest]

    def get_gateway_status(self) -> Dict[str, Any]:
        """One aggregate for every gateway surface: per-replica
        snapshots plus cluster totals (requests by outcome, per-class
        accept/complete/shed/disconnect split, status-code histogram,
        preemptions)."""
        with self._lock:
            gateways = {k: dict(v)
                        for k, v in self._gateway_stats.items()}
        by_class: Dict[str, Dict[str, int]] = {}
        by_code: Dict[str, int] = {}
        for g in gateways.values():
            for cls, row in (g.get("by_class") or {}).items():
                agg = by_class.setdefault(cls, {})
                for k, v in row.items():
                    agg[k] = agg.get(k, 0) + int(v)
            for code, n in (g.get("by_code") or {}).items():
                by_code[code] = by_code.get(code, 0) + int(n)
        totals: Dict[str, Any] = {
            "gateways": len(gateways),
            "by_class": by_class,
            "by_code": by_code,
        }
        for key in ("accepted", "completed", "streamed", "tokens_out",
                    "rate_limited", "sheds", "disconnects", "errors",
                    "preemptions"):
            totals[key] = sum(int(g.get(key, 0))
                              for g in gateways.values())
        return {"gateways": gateways, "totals": totals}

    def report_gateway_event(self, event: Dict[str, Any]) -> None:
        """accept / first_byte / preempt / rate_limit / disconnect
        instant markers for the merged timeline's gateway lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._gateway_events.append(event)
            if len(self._gateway_events) > self._GATEWAY_EVENTS_KEPT:
                del self._gateway_events[
                    :len(self._gateway_events)
                    - self._GATEWAY_EVENTS_KEPT]

    def get_gateway_events(self, limit: int = 10_000
                           ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._gateway_events[-limit:]

    # ------------------------------------------ per-request flight recorder
    # RequestTraceStores (observability/requests.py) push retention /
    # outcome counters plus a compact per-request summary window (the
    # unbiased p99-attribution population); every KEPT full trace rides
    # the event log as a kind="trace" record so `ray_tpu requests
    # --trace <id>` and the merged timeline's `requests` lane replay
    # its phase spans. Remote tier hops (actor-mode prefill/decode)
    # push kind="phase" child records under the same request id.
    # util.state.requesttrace_status(), `ray_tpu requests`, and
    # /api/requesttrace all read the same aggregate.

    _REQTRACE_EVENTS_KEPT = 10_000
    _REQTRACE_STATS_KEPT = 64

    def report_requesttrace_stats(self, worker_id: str,
                                  component_id: str,
                                  stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._requesttrace_stats[str(component_id)] = dict(
                stats, worker_id=worker_id,
                component_id=str(component_id), ts=time.time())
            while len(self._requesttrace_stats) \
                    > self._REQTRACE_STATS_KEPT:
                oldest = min(self._requesttrace_stats,
                             key=lambda k:
                             self._requesttrace_stats[k].get("ts", 0.0))
                del self._requesttrace_stats[oldest]

    def get_requesttrace_status(self) -> Dict[str, Any]:
        """One aggregate for every request-trace surface: per-store
        snapshots, cluster totals (completed/kept/dropped, outcome
        tally, replay + preempt counts), the cluster-wide slowest
        list, and a p99-attribution report recomputed over the merged
        per-component summary windows so the tail owner is named from
        the whole population, not one process's slice."""
        with self._lock:
            stores = {k: dict(v)
                      for k, v in self._requesttrace_stats.items()}
        totals: Dict[str, Any] = {"stores": len(stores)}
        for key in ("completed", "kept", "dropped", "replayed_requests",
                    "preempted_requests"):
            totals[key] = sum(int(s.get(key, 0))
                              for s in stores.values())
        outcomes: Dict[str, int] = {}
        slowest: List[Dict[str, Any]] = []
        merged_recent: List[Dict[str, Any]] = []
        for s in stores.values():
            for k, v in (s.get("outcomes") or {}).items():
                outcomes[k] = outcomes.get(k, 0) + int(v)
            slowest.extend(s.get("slowest") or [])
            merged_recent.extend(s.get("recent") or [])
        totals["outcomes"] = outcomes
        totals["slowest_ms"] = max(
            [float(s.get("slowest_ms", 0.0)) for s in stores.values()],
            default=0.0)
        slowest.sort(key=lambda r: float(r.get("total_ms") or 0.0),
                     reverse=True)
        from ray_tpu.observability.requests import p99_attribution

        return {"stores": stores, "totals": totals,
                "slowest": slowest[:32],
                "attribution": p99_attribution(merged_recent)}

    def report_requesttrace_event(self, event: Dict[str, Any]) -> None:
        """kind="trace" kept-trace records (full phase breakdowns) and
        kind="phase" remote child spans for the merged timeline's
        requests lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._requesttrace_events.append(event)
            if len(self._requesttrace_events) \
                    > self._REQTRACE_EVENTS_KEPT:
                del self._requesttrace_events[
                    :len(self._requesttrace_events)
                    - self._REQTRACE_EVENTS_KEPT]

    def get_requesttrace_events(self, limit: int = 10_000
                                ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._requesttrace_events[-limit:]

    def get_request_trace(self, request_id: str
                          ) -> Optional[Dict[str, Any]]:
        """Replay one request's full trace: the newest kept
        kind="trace" record under the id, with any kind="phase" child
        records remote tiers pushed merged in (attempt-tagged, so
        failover replays read as child spans under the same id)."""
        rid = str(request_id)
        with self._lock:
            events = list(self._requesttrace_events)
        trace = None
        for ev in reversed(events):
            if ev.get("kind") == "trace" \
                    and str(ev.get("request_id")) == rid:
                trace = dict(ev)
                break
        if trace is None:
            return None
        remote = [dict(ev) for ev in events
                  if ev.get("kind") == "phase"
                  and str(ev.get("request_id")) == rid]
        if remote:
            trace["remote_phases"] = remote
        return trace

    # ------------------------------------------ serving fault tolerance
    # Disagg routers (failover/shed accounting) and self-healers
    # (death/replacement/breaker counters) push snapshots here;
    # util.state.servefault_status(), `ray_tpu servefault`, and the
    # dashboard /api/servefault all read the same aggregate. The
    # instant markers (failover / replace / breaker_trip) land in the
    # resilience event log — recovery events belong in the resilience
    # lane of the merged timeline.

    _SERVEFAULT_STATS_KEPT = 128
    _SERVEFAULT_EVENT_KINDS = ("failover", "replace", "breaker_trip",
                               "replica_death", "chaos", "serve_drain")

    def report_servefault_stats(self, worker_id: str, component_id: str,
                                stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._servefault_stats[str(component_id)] = dict(
                stats, worker_id=worker_id,
                component_id=str(component_id), ts=time.time())
            while len(self._servefault_stats) > \
                    self._SERVEFAULT_STATS_KEPT:
                oldest = min(self._servefault_stats,
                             key=lambda k:
                             self._servefault_stats[k].get("ts", 0.0))
                del self._servefault_stats[oldest]

    def get_servefault_status(self) -> Dict[str, Any]:
        """One aggregate for every servefault surface: router snapshots
        (failovers by phase, sheds by cause, corpses removed) + healer
        snapshots (deaths, replacements, breaker) + cluster totals."""
        with self._lock:
            comps = {k: dict(v)
                     for k, v in self._servefault_stats.items()}
        routers = {k: v for k, v in comps.items()
                   if v.get("role") == "router"}
        healers = {k: v for k, v in comps.items()
                   if v.get("role") == "healer"}
        tiers = ("prefill", "decode")

        def _sum_tiered(snaps, key):
            return {t: sum(int((s.get(key) or {}).get(t, 0))
                           for s in snaps.values()) for t in tiers}

        sheds_by_cause: Dict[str, int] = {}
        for r in routers.values():
            for cause, n in (r.get("sheds_by_cause") or {}).items():
                sheds_by_cause[cause] = \
                    sheds_by_cause.get(cause, 0) + int(n)
        totals: Dict[str, Any] = {
            "routers": len(routers),
            "healers": len(healers),
            "failovers": _sum_tiered(routers, "failovers"),
            "failovers_total": sum(
                sum((r.get("failovers") or {}).values())
                for r in routers.values()),
            "failover_requests": sum(
                int(r.get("failover_requests", 0))
                for r in routers.values()),
            "sheds_by_cause": sheds_by_cause,
            "removed_dead": _sum_tiered(routers, "removed_dead"),
            "deaths": _sum_tiered(healers, "deaths"),
            "replacements": _sum_tiered(healers, "replacements"),
            "replacements_total": sum(
                sum((h.get("replacements") or {}).values())
                for h in healers.values()),
            "replacements_blocked": sum(
                int(h.get("replacements_blocked", 0))
                for h in healers.values()),
            "breaker_trips": sum(int(h.get("breaker_trips", 0))
                                 for h in healers.values()),
            "drains_reaped": sum(int(h.get("drains_reaped", 0))
                                 for h in healers.values()),
        }
        return {"routers": routers, "healers": healers,
                "totals": totals}

    def get_servefault_events(self, limit: int = 10_000
                              ) -> List[Dict[str, Any]]:
        """The servefault slice of the resilience event log (the
        markers live there — one lane, one set of numbers)."""
        with self._lock:
            events = list(self._resilience_events)
        kinds = self._SERVEFAULT_EVENT_KINDS
        return [e for e in events if e.get("kind") in kinds][-limit:]

    # -------------------------------------------- multi-tenant LoRA
    # Adapter pools (serve/lora.py AdapterPool — one per prefill /
    # decode replica or colocated engine) push paging snapshots,
    # routers push per-tenant request counters;
    # util.state.lora_status(), `ray_tpu lora`, and /api/lora all read
    # the same aggregate so every surface reports one set of numbers.

    _LORA_STATS_KEPT = 256
    _LORA_EVENTS_KEPT = 10_000

    def report_lora_stats(self, worker_id: str, component_id: str,
                          stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._lora_stats[str(component_id)] = dict(
                stats, worker_id=worker_id,
                component_id=str(component_id), ts=time.time())
            while len(self._lora_stats) > self._LORA_STATS_KEPT:
                oldest = min(self._lora_stats,
                             key=lambda k:
                             self._lora_stats[k].get("ts", 0.0))
                del self._lora_stats[oldest]

    def get_lora_status(self) -> Dict[str, Any]:
        """One aggregate for every lora surface: pool snapshots (pool
        paging counters + residents), router tenant counters, plus
        cluster totals (acquires/hits/misses/evictions/swaps/page-in
        bytes, per-tenant request rollup)."""
        with self._lock:
            comps = {k: dict(v) for k, v in self._lora_stats.items()}
        pools = {k: v for k, v in comps.items()
                 if v.get("role") == "pool"}
        routers = {k: v for k, v in comps.items()
                   if v.get("role") == "router"}
        tenants: Dict[str, Dict[str, Any]] = {}
        for p in pools.values():
            for t, ts in (p.get("tenants") or {}).items():
                agg = tenants.setdefault(
                    t, {"hits": 0, "misses": 0, "evictions": 0,
                        "swaps": 0, "dispatched": 0, "completed": 0,
                        "shed": 0, "slo_misses": 0})
                for key in ("hits", "misses", "evictions", "swaps"):
                    agg[key] += int(ts.get(key, 0))
        for r in routers.values():
            for t, ts in (r.get("tenants") or {}).items():
                agg = tenants.setdefault(
                    t, {"hits": 0, "misses": 0, "evictions": 0,
                        "swaps": 0, "dispatched": 0, "completed": 0,
                        "shed": 0, "slo_misses": 0})
                for key in ("dispatched", "completed", "shed",
                            "slo_misses"):
                    agg[key] += int(ts.get(key, 0))
        acquires = sum(int(p.get("acquires", 0))
                       for p in pools.values())
        hits = sum(int(p.get("hits", 0)) for p in pools.values())
        totals: Dict[str, Any] = {
            "pools": len(pools),
            "routers": len(routers),
            "slots": sum(int(p.get("slots", 0))
                         for p in pools.values()),
            "resident": sum(int(p.get("resident", 0))
                            for p in pools.values()),
            "pinned": sum(int(p.get("pinned", 0))
                          for p in pools.values()),
            "acquires": acquires,
            "hits": hits,
            "misses": sum(int(p.get("misses", 0))
                          for p in pools.values()),
            "evictions": sum(int(p.get("evictions", 0))
                             for p in pools.values()),
            "swaps": sum(int(p.get("swaps", 0))
                         for p in pools.values()),
            "page_in_bytes": sum(int(p.get("page_in_bytes", 0))
                                 for p in pools.values()),
            "hit_rate": hits / acquires if acquires else 0.0,
            "tenants": len(tenants),
        }
        return {"pools": pools, "routers": routers,
                "tenants": tenants, "totals": totals}

    def report_lora_event(self, event: Dict[str, Any]) -> None:
        """page_in / evict / swap instant markers for the merged
        timeline's lora lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._lora_events.append(event)
            if len(self._lora_events) > self._LORA_EVENTS_KEPT:
                del self._lora_events[
                    :len(self._lora_events) - self._LORA_EVENTS_KEPT]

    def get_lora_events(self, limit: int = 10_000
                        ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._lora_events[-limit:]

    # ------------------------------------------------ serving autoscaler
    # serve/autoscale.py policy loops push status snapshots and
    # scale_up/scale_down/drain instant markers here;
    # util.state.autoscaler_status(), `ray_tpu autoscale`, and the
    # dashboard /api/autoscale all read the same aggregate so every
    # surface reports one set of numbers.

    _AUTOSCALE_STATS_KEPT = 64
    _AUTOSCALE_EVENTS_KEPT = 10_000

    def report_autoscale_stats(self, worker_id: str, autoscaler_id: str,
                               stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict):
            return
        with self._lock:
            self._autoscale_stats[str(autoscaler_id)] = dict(
                stats, worker_id=worker_id,
                autoscaler_id=str(autoscaler_id), ts=time.time())
            while len(self._autoscale_stats) > self._AUTOSCALE_STATS_KEPT:
                oldest = min(self._autoscale_stats,
                             key=lambda k:
                             self._autoscale_stats[k].get("ts", 0.0))
                del self._autoscale_stats[oldest]

    def get_autoscale_status(self) -> Dict[str, Any]:
        """One aggregate for every autoscale surface: per-loop status
        snapshots plus cluster totals (decisions by direction, drains,
        replica-seconds per tier, current targets)."""
        with self._lock:
            loops = {k: dict(v)
                     for k, v in self._autoscale_stats.items()}
        totals: Dict[str, Any] = {
            "autoscalers": len(loops),
            "scale_ups": sum(sum(s.get("scale_ups", {}).values())
                             for s in loops.values()),
            "scale_downs": sum(sum(s.get("scale_downs", {}).values())
                               for s in loops.values()),
            "drains_completed": sum(int(s.get("drains_completed", 0))
                                    for s in loops.values()),
            "drains_forced": sum(int(s.get("drains_forced", 0))
                                 for s in loops.values()),
            "replica_seconds": {
                tier: round(sum(
                    float(s.get("replica_seconds", {}).get(tier, 0.0))
                    for s in loops.values()), 3)
                for tier in ("prefill", "decode")},
            "active_replicas": {
                tier: sum(int(s.get(f"{tier}_active", 0))
                          for s in loops.values())
                for tier in ("prefill", "decode")},
        }
        return {"autoscalers": loops, "totals": totals}

    def report_autoscale_event(self, event: Dict[str, Any]) -> None:
        """scale_up / scale_down / drain instant markers for the merged
        timeline's autoscale lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            event = dict(event)
            event.setdefault("ts", time.time())
            self._autoscale_events.append(event)
            if len(self._autoscale_events) > self._AUTOSCALE_EVENTS_KEPT:
                del self._autoscale_events[
                    :len(self._autoscale_events)
                    - self._AUTOSCALE_EVENTS_KEPT]

    def get_autoscale_events(self, limit: int = 10_000
                             ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._autoscale_events[-limit:]

    # ------------------------------------------------- step-time oracle
    # observability.roofline pushes layout predictions and validation
    # records here; util.state.oracle_status(), `ray_tpu oracle`, and
    # the dashboard /api/oracle all read the same aggregate so every
    # surface reports one set of numbers. Events feed the merged
    # timeline's predicted-step-time counter track.

    _ORACLE_PREDICTIONS_KEPT = 256
    _ORACLE_VALIDATIONS_KEPT = 1024
    _ORACLE_EVENTS_KEPT = 10_000

    def _oracle_event_locked(self, event: Dict[str, Any]) -> None:
        event.setdefault("ts", time.time())
        self._oracle_events.append(event)
        if len(self._oracle_events) > self._ORACLE_EVENTS_KEPT:
            del self._oracle_events[
                :len(self._oracle_events) - self._ORACLE_EVENTS_KEPT]

    def report_oracle_prediction(self, worker_id: str, layout: str,
                                 prediction: Dict[str, Any]) -> None:
        if not isinstance(prediction, dict):
            return
        with self._lock:
            rec = dict(prediction, layout=str(layout),
                       worker_id=worker_id, ts=time.time())
            self._oracle_predictions[str(layout)] = rec
            while len(self._oracle_predictions) > \
                    self._ORACLE_PREDICTIONS_KEPT:
                oldest = min(self._oracle_predictions,
                             key=lambda k:
                             self._oracle_predictions[k].get("ts", 0.0))
                del self._oracle_predictions[oldest]
            self._oracle_event_locked(dict(
                kind="prediction", layout=str(layout),
                predicted_step_ms=prediction.get("predicted_step_ms"),
                device_step_ms=prediction.get("device_step_ms"),
                ici_wait_ms=prediction.get("ici_wait_ms"),
                dcn_wait_ms=prediction.get("dcn_wait_ms")))

    def report_oracle_validation(self, worker_id: str,
                                 rec: Dict[str, Any]) -> None:
        if not isinstance(rec, dict):
            return
        with self._lock:
            rec = dict(rec, worker_id=worker_id, ts=time.time())
            self._oracle_validations.append(rec)
            if len(self._oracle_validations) > \
                    self._ORACLE_VALIDATIONS_KEPT:
                del self._oracle_validations[
                    :len(self._oracle_validations)
                    - self._ORACLE_VALIDATIONS_KEPT]
            self._oracle_event_locked(dict(
                kind="validation", layout=rec.get("layout"),
                run_id=rec.get("run_id"),
                calibration=rec.get("calibration"),
                residuals=rec.get("residuals"),
                n_steps=rec.get("n_steps")))

    def get_oracle_status(self) -> Dict[str, Any]:
        """One aggregate for every oracle surface: the latest prediction
        per layout, the validation tail, and totals (counts + the last
        fitted calibration and its worst phase residual)."""
        with self._lock:
            preds = {k: dict(v)
                     for k, v in self._oracle_predictions.items()}
            vals = [dict(v) for v in self._oracle_validations[-100:]]
            n_validations = len(self._oracle_validations)
        last = vals[-1] if vals else {}
        residuals = last.get("residuals") or {}
        totals: Dict[str, Any] = {
            "layouts": len(preds),
            "validations": n_validations,
            "last_calibration": last.get("calibration"),
            "worst_residual_ratio": max(
                (float(r) for r in residuals.values()), default=None,
                key=lambda r: abs(r - 1.0)),
        }
        return {"predictions": preds, "validations": vals,
                "totals": totals}

    def get_oracle_events(self, limit: int = 10_000
                          ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._oracle_events[-limit:]

    # ------------------------------------------------------ MPMD pipelines
    # ray_tpu.mpmd: stage registry, channel mailbox, per-stage stats and
    # instant markers. util.state.pipeline_status(), `ray_tpu pipeline`,
    # and the dashboard /api/pipeline all read get_pipeline_status so
    # every surface reports one set of numbers.

    _PIPELINE_EVENTS_KEPT = 10_000
    _PIPELINE_MAILBOX_CAP = 65_536
    _PIPELINES_KEPT = 16  # closed records retained (open ones never evict)

    def _pipeline_event_locked(self, event: Dict[str, Any]) -> None:
        event.setdefault("ts", time.time())
        self._pipeline_events.append(event)
        if len(self._pipeline_events) > self._PIPELINE_EVENTS_KEPT:
            del self._pipeline_events[
                :len(self._pipeline_events)
                - self._PIPELINE_EVENTS_KEPT]

    def pipeline_open(self, name: str,
                      spec: Dict[str, Any]) -> Dict[str, Any]:
        """Create (or replace) a pipeline registry entry. Reopening a
        name drops the previous generation's stages, stats, and any
        stale mailbox entries — a restarted driver must not deliver the
        dead run's activations."""
        num_stages = int(spec.get("num_stages", 0))
        if num_stages < 2:
            return {"error": f"num_stages must be >= 2, got "
                             f"{num_stages}"}
        if "/ch/" in name or name.endswith("/ch"):
            # "/ch/" delimits channel keys (pipeline_channel_put parses
            # the name back out of the key at its FIRST occurrence, and
            # a name ending in "/ch" would shift that occurrence)
            return {"error": f"pipeline name {name!r} must not "
                             "contain '/ch/' or end with '/ch'"}
        with self._lock:
            # "/ch/" delimiter (not a bare "/") so purging "train"
            # never touches a live "train/eval" pipeline's entries
            prefix = f"{name}/ch/"
            for key in [k for k in self._pipeline_mailbox
                        if k.startswith(prefix)]:
                del self._pipeline_mailbox[key]
            self._pipelines[name] = {
                "name": name,
                "num_stages": num_stages,
                "schedule": spec.get("schedule", "1f1b"),
                "num_microbatches": spec.get("num_microbatches"),
                "bubble_estimate": spec.get("bubble_estimate"),
                "run_id": spec.get("run_id", ""),
                "created": time.time(),
                "formed": False,
                "closed": False,
                "stages": {},
                "stats": {},
            }
            self._pipeline_event_locked(
                {"kind": "open", "pipeline": name,
                 "num_stages": num_stages,
                 "schedule": spec.get("schedule")})
        return {"ok": True}

    def pipeline_register_stage(self, name: str, stage: int,
                                info: Dict[str, Any]) -> Dict[str, Any]:
        """One stage-gang's registration. The pipeline flips formed=True
        atomically when the LAST of num_stages stages is in — partial
        pipelines are never visible as formed (the weights-fragment
        commit pattern)."""
        formed_now = False
        with self._lock:
            rec = self._pipelines.get(name)
            if rec is None or rec.get("closed"):
                return {"error": f"no open pipeline {name!r} — call "
                                 "pipeline_open first"}
            stage = int(stage)
            if not 0 <= stage < rec["num_stages"]:
                return {"error": f"stage {stage} out of range for "
                                 f"{rec['num_stages']}-stage pipeline"}
            reg_run = (info or {}).get("run_id")
            if rec.get("run_id") and reg_run is not None and \
                    reg_run != rec["run_id"]:
                # a stage from a DEAD generation (driver restarted and
                # reopened the name) must not count toward — or flip —
                # this generation's formation
                return {"error":
                        f"stage {stage} belongs to generation "
                        f"{reg_run!r}, not {rec['run_id']!r}"}
            rec["stages"][stage] = dict(info or {}, ts=time.time())
            self._pipeline_event_locked(
                {"kind": "stage_registered", "pipeline": name,
                 "stage": stage,
                 "slice_id": (info or {}).get("slice_id")})
            if not rec["formed"] and \
                    len(rec["stages"]) == rec["num_stages"]:
                rec["formed"] = True
                formed_now = True
                self._pipeline_event_locked(
                    {"kind": "formed", "pipeline": name,
                     "num_stages": rec["num_stages"]})
            formed = rec["formed"]
        if formed_now:
            self.publish("pipeline", {"kind": "formed", "name": name})
        return {"ok": True, "formed": formed}

    def pipeline_get(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._pipelines.get(name)
            if rec is None:
                return None
            out = dict(rec)
            out["stages"] = {s: dict(v)
                             for s, v in rec["stages"].items()}
            out["stats"] = {s: dict(v) for s, v in rec["stats"].items()}
            return out

    def pipeline_close(self, name: str) -> bool:
        """Mark the pipeline closed and drop its mailbox entries (the
        senders' chunk refs die with the stage actors)."""
        with self._lock:
            rec = self._pipelines.get(name)
            if rec is None:
                return False
            rec["closed"] = True
            prefix = f"{name}/ch/"
            dropped = [k for k in self._pipeline_mailbox
                       if k.startswith(prefix)]
            for key in dropped:
                del self._pipeline_mailbox[key]
            self._pipeline_event_locked(
                {"kind": "closed", "pipeline": name,
                 "dropped_mailbox": len(dropped)})
            # keep-last-K of CLOSED records (the weights-registry GC
            # pattern): a sweep of uniquely-named runs must not grow
            # the registry — and every status payload — forever
            closed = sorted(
                (n for n, r in self._pipelines.items()
                 if r.get("closed")),
                key=lambda n: self._pipelines[n].get("created", 0.0))
            for n in closed[:max(0, len(closed) - self._PIPELINES_KEPT)]:
                del self._pipelines[n]
        return True

    def pipeline_channel_put(self, key: str,
                             desc: Dict[str, Any]) -> Dict[str, Any]:
        """Register one microbatch payload's chunk descriptor
        (metadata only). Single-slot per key: the schedules never
        produce the same (step, mb, kind) twice."""
        if not isinstance(desc, dict):
            return {"error": "descriptor must be a dict"}
        from ray_tpu.util.runtime import pipeline_run_token

        name, _, rest = str(key).partition("/ch/")
        with self._lock:
            rec = self._pipelines.get(name)
            if rec is None or rec.get("closed"):
                # a stage-gang of a closed/GC-evicted (dead) generation
                # must fail its sends instead of leaking undeliverable
                # entries toward the global mailbox cap
                return {"error": f"pipeline {name!r} is not open — "
                                 "pipeline_open must precede channel "
                                 "sends"}
            run = rest.split("/", 1)[0]
            want = pipeline_run_token(str(rec["run_id"])) \
                if rec.get("run_id") else ""
            if want and run != want:
                # same generation fencing as stage registration: an
                # orphaned old gang's sends must fail fast, not pile
                # up as undeliverable entries under the live name
                return {"error":
                        f"channel key belongs to generation {run!r}, "
                        f"not {want!r}"}
            if len(self._pipeline_mailbox) >= self._PIPELINE_MAILBOX_CAP:
                return {"error":
                        f"pipeline mailbox full "
                        f"({self._PIPELINE_MAILBOX_CAP} entries) — "
                        "receiver stages dead or wedged?"}
            self._pipeline_mailbox[str(key)] = desc
        self.publish("pipeline", {"kind": "channel_put", "key": key})
        return {"ok": True}

    def pipeline_channel_pending(self, keys: List[str]) -> List[str]:
        """Which of `keys` are still undelivered (the sender-side
        drain barrier — see ActivationChannel.drain)."""
        with self._lock:
            return [k for k in keys if str(k) in self._pipeline_mailbox]

    def pipeline_channel_discard(self, keys: List[str]) -> None:
        """Drop undelivered descriptors whose chunks the sender is
        about to free (retention pruning / channel close): a
        descriptor naming freed chunks must not stay deliverable —
        a late recv would die in an opaque fetch timeout — nor leak
        toward the mailbox cap."""
        with self._lock:
            for k in keys:
                self._pipeline_mailbox.pop(str(k), None)

    def pipeline_channel_take(self, key: str) -> Optional[Dict[str, Any]]:
        """Pop a descriptor (None while not yet delivered — receivers
        poll with a pubsub wakeup)."""
        with self._lock:
            return self._pipeline_mailbox.pop(str(key), None)

    def report_pipeline_stats(self, name: str, stage: int,
                              stats: Dict[str, Any]) -> None:
        """Per-stage run summary (bubble fraction, channel bytes,
        steps) from the stage-gangs — the one set of numbers every
        surface reports."""
        if not isinstance(stats, dict):
            return
        with self._lock:
            rec = self._pipelines.get(name)
            if rec is None:
                return
            run = stats.get("run_id")
            if rec.get("run_id") and run is not None and \
                    run != rec["run_id"]:
                # a dead generation must not overwrite the live run's
                # numbers (generation fencing, as registration)
                return
            rec["stats"][int(stage)] = dict(stats, ts=time.time())

    def get_pipeline_status(self) -> Dict[str, Any]:
        """State-API/dashboard view: every pipeline's registry record
        plus cross-stage totals (activation bytes, mean/max bubble)."""
        with self._lock:
            pipelines = {}
            for name, rec in self._pipelines.items():
                out = dict(rec)
                out["stages"] = {s: dict(v)
                                 for s, v in rec["stages"].items()}
                out["stats"] = {s: dict(v)
                                for s, v in rec["stats"].items()}
                pipelines[name] = out
            mailbox_depth = len(self._pipeline_mailbox)
        for rec in pipelines.values():
            stats = rec["stats"].values()
            fracs = [s.get("bubble_fraction") for s in stats
                     if s.get("bubble_fraction") is not None]
            rec["totals"] = {
                "activation_bytes": sum(int(s.get("sent_bytes") or 0)
                                        for s in stats),
                "bubble_fraction_mean": (sum(fracs) / len(fracs)
                                         if fracs else None),
                "bubble_fraction_max": max(fracs) if fracs else None,
                "steps": max((int(s.get("steps") or 0) for s in stats),
                             default=0),
            }
        return {"pipelines": pipelines, "mailbox_depth": mailbox_depth}

    def report_pipeline_event(self, event: Dict[str, Any]) -> None:
        """Instant markers (formed / stage_report / stage_death /
        closed) for the merged timeline's pipeline lane."""
        if not isinstance(event, dict):
            return
        with self._lock:
            self._pipeline_event_locked(dict(event))

    def get_pipeline_events(self, limit: int = 10_000
                            ) -> List[Dict[str, Any]]:
        with self._lock:
            return self._pipeline_events[-limit:]

    def weights_publish_fragment(self, name: str, version: int, host: int,
                                 num_hosts: int, fragment: Dict[str, Any],
                                 run_id: str = "",
                                 step: Optional[int] = None
                                 ) -> Dict[str, Any]:
        """One host's share of a publish: per-leaf shard metadata (the
        chunk ObjectIDs live in that host's store). The version flips
        committed — and becomes fetchable — only when all `num_hosts`
        fragments are in; until then it is invisible to subscribers and
        a died-mid-publish producer leaves only a reapable pending
        entry, never a torn manifest."""
        version = int(version)
        publish_msg = None
        gc_msgs: List[Dict[str, Any]] = []
        with self._cv:
            by_ver = self._weights_committed.setdefault(name, {})
            if version in by_ver:
                return {"error": f"version {version} of {name!r} is "
                                 "already committed"}
            base_version = fragment.get("base_version")
            if base_version is not None \
                    and int(base_version) not in by_ver:
                # delta against a base this registry no longer holds
                # (GC'd between the publisher's probe and this call):
                # reject so the publisher's full fallback runs — an
                # inherit-from-nothing commit would be a torn manifest
                return {"error": f"delta base {base_version} of "
                                 f"{name!r} is gone"}
            key = (name, version)
            pend = self._weights_pending.get(key)
            if pend is not None and int(num_hosts) != pend["num_hosts"]:
                # a gang RESIZED between attempts (elastic re-form after
                # a crash that left this version partially published):
                # the stale pending entry can never complete under the
                # old num_hosts, and erroring here would crash-loop the
                # recovered gang until the TTL reaper ran — supersede
                # it, telling the old fragments' owners to free EXACTLY
                # those chunks (by object id: the new gang's in-flight
                # chunks share the version number and must survive)
                gc_msgs.append({
                    "kind": "reaped", "name": name,
                    "versions": [version],
                    "object_ids": self._weights_object_ids(
                        f["leaves"] for f in
                        pend["fragments"].values())})
                self._weight_event_locked(
                    {"kind": "reap", "name": name, "version": version,
                     "detail": f"superseded: num_hosts "
                               f"{pend['num_hosts']} -> {num_hosts}"})
                pend = None
            if pend is None:
                pend = self._weights_pending[key] = {
                    "fragments": {}, "num_hosts": int(num_hosts),
                    "run_id": run_id, "step": step,
                    "started": time.monotonic()}
            prev_frag = pend["fragments"].get(int(host))
            if prev_frag is not None:
                # fragment RESEND (publisher retry after an ambiguous
                # RPC timeout): the replaced fragment's chunks are
                # referenced by nothing from here on — reap-notice them
                # or the producer pins a full stale shard copy forever
                gc_msgs.append({
                    "kind": "reaped", "name": name,
                    "versions": [version],
                    "object_ids": self._weights_object_ids(
                        [prev_frag["leaves"]])})
            pend["fragments"][int(host)] = fragment
            self._dirty = True  # registry is a durable table: producers'
            # chunk refs depend on gc/reap notices that only a registry
            # remembering the version can ever send (conductor bounce)
            committed = len(pend["fragments"]) == pend["num_hosts"]
            error = None
            if committed:
                # delta commits inherit unchanged leaves from their base
                # manifests — every named base must still be here (a
                # fragment-time check passed, but another host's base
                # could have been GC'd while this publish was pending)
                gone = sorted({int(f["base_version"])
                               for f in pend["fragments"].values()
                               if f.get("base_version") is not None
                               and int(f["base_version"]) not in by_ver})
                if gone:
                    del self._weights_pending[key]
                    gc_msgs.append({
                        "kind": "reaped", "name": name,
                        "versions": [version],
                        "object_ids": self._weights_object_ids(
                            f["leaves"] for f in
                            pend["fragments"].values())})
                    self._weight_event_locked(
                        {"kind": "reap", "name": name,
                         "version": version,
                         "detail": f"delta base {gone} gone"})
                    error = (f"delta base {gone[0]} of {name!r} is "
                             "gone")
                else:
                    del self._weights_pending[key]
                    manifest = self._weights_commit_locked(name, version,
                                                           pend)
                    publish_msg = {"kind": "published", "name": name,
                                   "version": version, "step": step,
                                   "run_id": run_id,
                                   "total_bytes":
                                       manifest["total_bytes"]}
                    # EXTEND: a supersede notice queued above must still
                    # go out when the superseding fragment commits
                    # immediately
                    gc_msgs.extend(self._weights_gc_locked(name, None))
            self._notify_all_locked()
        if publish_msg is not None:
            self.publish("weights", publish_msg)
        for msg in gc_msgs:
            self.publish("weights", msg)
        if error is not None:
            return {"error": error}
        return {"committed": committed, "version": version}

    @staticmethod
    def _weights_object_ids(leaves_by_frag) -> List[str]:
        """Chunk object ids referenced by fragments or manifest leaves.
        gc/reap notices name EXPLICIT object ids so a publisher only
        ever frees the chunks the registry actually dropped — a
        version-scoped notice would also hit a NEW publish in flight
        under the same version number (gang resize supersede)."""
        out: List[str] = []
        for leaves in leaves_by_frag:
            for leaf in (leaves.values() if isinstance(leaves, dict)
                         else leaves):
                for sh in leaf.get("shards", ()):
                    out.append(sh["object_id"])
        return out

    @staticmethod
    def _weights_recency(manifest: Dict[str, Any]) -> Tuple[float, int]:
        """Ordering key for GC and 'latest': commit recency, version as
        tiebreak. By COMMIT TIME, not version number — a gang restarted
        from an older checkpoint legitimately republishes lower version
        numbers, and those are the weights subscribers should follow
        (max-version ordering would instantly GC the rollback's publish
        while 'latest' kept pointing at the dead attempt's weights)."""
        return (float(manifest.get("ts", 0.0)),
                int(manifest.get("version", 0)))

    def _weights_latest_locked(self, name: str) -> Optional[int]:
        by_ver = self._weights_committed.get(name, {})
        if not by_ver:
            return None
        return max(by_ver.values(), key=self._weights_recency)["version"]

    def weights_latest_version(self, name: str) -> Optional[int]:
        """O(1)-payload poll target for subscribers — the full manifest
        (per-chunk tables + treedef bytes) must not ship on every
        staleness check."""
        with self._lock:
            return self._weights_latest_locked(name)

    def weights_has_version(self, name: str, version: int) -> bool:
        """O(1) committed-version probe (publishers pre-check replayed
        steps before paying the local shard copy into the store)."""
        with self._lock:
            return int(version) in self._weights_committed.get(name, {})

    def _weights_commit_locked(self, name: str, version: int,
                               pend: Dict[str, Any]) -> Dict[str, Any]:
        """Merge host fragments into the version manifest. Must hold the
        lock; records the publish event.

        Delta fragments mark unchanged leaves ``from_base``: those
        inherit the named base manifest's chunk entries FOR THAT HOST
        (entries are host-tagged at commit exactly so this attribution
        survives the merge). The committed manifest is therefore always
        self-contained — chains of deltas collapse one link per commit,
        and a version stays fetchable no matter which of its ancestors
        GC later drops. ``delta_bytes`` records what the publish
        actually shipped; ``total_bytes`` stays the full resolved
        size."""
        frags = pend["fragments"]
        by_ver = self._weights_committed.get(name, {})
        n_leaves = max(int(f.get("n_leaves", 0)) for f in frags.values())
        leaves: List[Dict[str, Any]] = []
        total = 0
        delta_bytes = 0
        n_chunks = 0
        changed: List[int] = []
        any_delta = any(f.get("base_version") is not None
                        for f in frags.values())
        for i in range(n_leaves):
            meta = None
            shards: List[Dict[str, Any]] = []
            leaf_changed = False
            for host, f in sorted(frags.items()):
                m = f["leaves"].get(str(i))
                if m is None:
                    continue
                meta = meta or m
                if m.get("from_base"):
                    base = by_ver[int(f["base_version"])]
                    shards.extend(
                        s for s in base["leaves"][i]["shards"]
                        if s.get("host", host) == host)
                else:
                    own = [dict(s, host=host) for s in m["shards"]]
                    shards.extend(own)
                    if own:
                        leaf_changed = True
                        delta_bytes += sum(int(s["nbytes"])
                                           for s in own)
            total += sum(int(s["nbytes"]) for s in shards)
            n_chunks += len(shards)
            if leaf_changed:
                changed.append(i)
            leaves.append({"shape": meta["shape"], "dtype": meta["dtype"],
                           "hash": meta.get("hash"), "shards": shards})
        treedef = next((f["treedef"] for _, f in sorted(frags.items())
                        if f.get("treedef") is not None), None)
        manifest = {"name": name, "version": version,
                    "step": pend.get("step"), "run_id": pend.get("run_id"),
                    "ts": time.time(), "num_hosts": pend["num_hosts"],
                    "n_leaves": n_leaves, "n_chunks": n_chunks,
                    "total_bytes": total, "leaves": leaves,
                    "treedef": treedef,
                    "delta": any_delta,
                    "base_version": next(
                        (int(f["base_version"]) for f in frags.values()
                         if f.get("base_version") is not None), None),
                    "changed_leaves": changed if any_delta else None,
                    "delta_bytes": delta_bytes}
        self._weights_committed[name][version] = manifest
        self._weight_event_locked(
            {"kind": "publish", "name": name, "version": version,
             "step": pend.get("step"), "run_id": pend.get("run_id"),
             "num_hosts": pend["num_hosts"], "bytes": total,
             "delta_bytes": delta_bytes if any_delta else None,
             "changed_leaves": len(changed) if any_delta else None})
        return manifest

    def _weights_live_ids_locked(self, name: str) -> set:
        """Chunk object ids referenced by the KEPT manifests and pending
        fragments of `name`. Delta manifests inherit their base's chunk
        entries, so dropping a base version must free only the ids no
        kept manifest still points at."""
        live = set(self._weights_object_ids(
            m["leaves"] for m in
            self._weights_committed.get(name, {}).values()))
        for (n, _v), pend in self._weights_pending.items():
            if n == name:
                live.update(self._weights_object_ids(
                    f["leaves"] for f in pend["fragments"].values()))
        return live

    def _weights_gc_locked(self, name: str,
                           keep: Optional[int]) -> List[Dict[str, Any]]:
        """Drop committed versions beyond keep-last-K (config
        weights_keep when `keep` is None). Returns the pubsub messages
        telling producers which versions' chunks to free — publish them
        AFTER releasing the lock. Ids still referenced by a kept
        manifest (delta inheritance) are withheld from the notice."""
        from .config import config

        keep = config.weights_keep if keep is None else int(keep)
        by_ver = self._weights_committed.get(name, {})
        order = sorted(by_ver,
                       key=lambda v: self._weights_recency(by_ver[v]))
        drop = order[:-keep] if keep > 0 else order
        msgs = []
        for v in drop:
            manifest = by_ver.pop(v)
            self._dirty = True
            self._weight_event_locked(
                {"kind": "gc", "name": name, "version": v})
            live = self._weights_live_ids_locked(name)
            dead = [oid for oid in self._weights_object_ids(
                        [manifest["leaves"]])
                    if oid not in live]
            msgs.append({"kind": "gc", "name": name, "versions": [v],
                         "object_ids": dead})
        return msgs

    def weights_gc(self, name: str, keep: Optional[int] = None) -> int:
        """Operator GC (`ray_tpu weights gc`): keep only the newest
        `keep` versions of `name`. Returns the number dropped. Only an
        EXPLICIT keep=0 drops everything; a negative keep (operator
        typo) is rejected rather than read as drop-all."""
        if keep is not None and int(keep) < 0:
            raise ValueError(f"keep must be >= 0, got {keep}")
        with self._cv:
            msgs = self._weights_gc_locked(name, keep)
            self._notify_all_locked()
        for msg in msgs:
            self.publish("weights", msg)
        return len(msgs)

    def weights_reap(self, max_age_s: Optional[float] = None) -> int:
        """Drop pending publishes older than `max_age_s` (config
        weights_publish_ttl_s default) — a producer chaos-killed
        mid-publish must never leave a forever-pending entry, and its
        surviving peers' orphan chunks must be freed. Runs from the
        monitor loop; tests call it with 0 for determinism."""
        from .config import config

        ttl = config.weights_publish_ttl_s if max_age_s is None \
            else float(max_age_s)
        now = time.monotonic()
        msgs = []
        with self._cv:
            for key in [k for k, p in self._weights_pending.items()
                        if now - p["started"] >= ttl]:
                name, version = key
                pend = self._weights_pending.pop(key)
                self._dirty = True
                self._weight_event_locked(
                    {"kind": "reap", "name": name, "version": version})
                msgs.append({"kind": "reaped", "name": name,
                             "versions": [version],
                             "object_ids": self._weights_object_ids(
                                 f["leaves"] for f in
                                 pend["fragments"].values())})
            if msgs:
                self._notify_all_locked()
        for msg in msgs:
            self.publish("weights", msg)
        return len(msgs)

    def weights_get_manifest(self, name: str,
                             version: Optional[int] = None
                             ) -> Optional[Dict[str, Any]]:
        """The full manifest of `version` (latest committed when None),
        or None when nothing is committed / the version was GC'd."""
        with self._lock:
            by_ver = self._weights_committed.get(name, {})
            if not by_ver:
                return None
            v = self._weights_latest_locked(name) if version is None \
                else int(version)
            return by_ver.get(v)

    def get_weight_versions(self) -> Dict[str, Any]:
        """Registry state for util.state.weight_versions(), the
        `ray_tpu weights` CLI, and the dashboard's /api/weights — one
        summary per name, manifests without the per-shard chunk lists."""
        with self._lock:
            out: Dict[str, Any] = {}
            for name, by_ver in self._weights_committed.items():
                if not by_ver:
                    continue
                out[name] = {
                    "latest": self._weights_latest_locked(name),
                    "versions": [
                        {k: m.get(k) for k in (
                            "version", "step", "run_id", "ts",
                            "num_hosts", "n_leaves", "n_chunks",
                            "total_bytes", "delta", "base_version",
                            "delta_bytes")}
                        for m in sorted(
                            by_ver.values(),
                            key=self._weights_recency)],
                }
            pending = [{"name": n, "version": v,
                        "hosts_committed":
                            sorted(p["fragments"]),
                        "num_hosts": p["num_hosts"],
                        "age_s": round(time.monotonic() - p["started"], 3)}
                       for (n, v), p in self._weights_pending.items()]
            return {"names": out, "pending": pending}

    # ----------------------------------------------------------- metrics
    # Reference: src/ray/stats/metric_exporter.cc -> metrics agent ->
    # Prometheus; here workers push their registry snapshots and the
    # conductor is the aggregation point the exporter reads.

    def report_metrics(self, worker_id: str,
                       snapshot: List[Dict[str, Any]]) -> None:
        with self._lock:
            if not hasattr(self, "_metrics"):
                self._metrics: Dict[str, List[Dict[str, Any]]] = {}
            self._metrics[worker_id] = snapshot

    def get_metrics(self) -> Dict[str, List[Dict[str, Any]]]:
        with self._lock:
            return dict(getattr(self, "_metrics", {}))

    # ------------------------------------------------------------------ jobs
    # Reference: GcsJobManager (src/ray/gcs/gcs_server/gcs_job_manager) +
    # dashboard/modules/job JobManager — entrypoint drivers run as head-node
    # subprocesses with RAY_TPU_ADDRESS injected, logs captured per job.

    def submit_job(self, entrypoint: str,
                   env: Optional[Dict[str, str]] = None,
                   submission_id: Optional[str] = None,
                   working_dir: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        import uuid as _uuid

        job_id = submission_id or f"job_{_uuid.uuid4().hex[:12]}"
        with self._lock:
            if job_id in getattr(self, "_jobs", {}):
                raise ValueError(
                    f"job submission id {job_id!r} already exists "
                    "(reference JobManager rejects duplicates)")
        logs = os.path.join(self._session_dir, "logs")
        os.makedirs(logs, exist_ok=True)
        log_path = os.path.join(logs, f"{job_id}.log")
        host, port = self.address
        penv = dict(os.environ)
        penv.update(env or {})
        penv["RAY_TPU_ADDRESS"] = f"{host}:{port}"
        penv["RAY_TPU_JOB_ID"] = job_id
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, env=penv,
                cwd=working_dir or os.getcwd(),
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True)
        finally:
            log_f.close()
        with self._lock:
            self._jobs[job_id] = {
                "job_id": job_id, "entrypoint": entrypoint,
                "start_time": time.time(), "end_time": None,
                "log_path": log_path, "proc": proc, "stopped": False,
                "metadata": dict(metadata or {})}
            self._dirty = True
        return job_id

    def _job_status_locked(self, rec: Dict[str, Any]) -> str:
        proc = rec["proc"]
        if proc is None:  # restored after a conductor restart
            return rec.get("status", "FAILED")
        code = proc.poll()
        if code is None:
            return "RUNNING"
        if rec["end_time"] is None:
            rec["end_time"] = time.time()
            self._dirty = True  # terminal status reached; persist it
        if rec["stopped"]:
            return "STOPPED"
        return "SUCCEEDED" if code == 0 else "FAILED"

    def get_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = getattr(self, "_jobs", {}).get(job_id)
            if rec is None:
                return None
            return {k: v for k, v in dict(
                rec, status=self._job_status_locked(rec)).items()
                if k != "proc"}

    def list_jobs(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{k: v for k, v in dict(
                r, status=self._job_status_locked(r)).items() if k != "proc"}
                for r in getattr(self, "_jobs", {}).values()]

    def stop_job(self, job_id: str) -> bool:
        with self._lock:
            rec = getattr(self, "_jobs", {}).get(job_id)
            if rec is None or rec["proc"] is None \
                    or rec["proc"].poll() is not None:
                return False
            rec["stopped"] = True
            proc = rec["proc"]
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (OSError, ProcessLookupError):
            proc.terminate()
        return True

    def get_job_logs(self, job_id: str, tail_bytes: int = 1 << 20) -> str:
        with self._lock:
            rec = getattr(self, "_jobs", {}).get(job_id)
        if rec is None:
            raise KeyError(job_id)
        try:
            with open(rec["log_path"], "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode("utf-8", "replace")
        except FileNotFoundError:
            return ""

    def shutdown_cluster(self) -> bool:
        """Remote stop for `ray_tpu stop` — tears the head down shortly
        after replying."""

        def later():
            time.sleep(0.2)
            try:
                self.stop()
            finally:
                os._exit(0)

        threading.Thread(target=later, daemon=True).start()
        return True

    # ------------------------------------------------------------------ misc

    def ping(self) -> str:
        return "pong"

    def session_info(self) -> Dict[str, Any]:
        from .worker import _MACHINE_ID

        return {"session_dir": self._session_dir,
                "head_node_id": self._head_node_id,
                "machine": _MACHINE_ID}

    # ----------------------------------------------------------- persistence

    def _flush_state(self) -> None:
        """Write the durable tables to disk (atomic rename). Called by the
        monitor when dirty and on stop — mutations only mark dirty, so the
        hot path never pays the disk write."""
        import pickle

        with self._lock:
            if not self._dirty:
                return
            self._dirty = False
            jobs = {}
            for jid, r in self._jobs.items():
                meta = {k: v for k, v in r.items() if k != "proc"}
                meta["status"] = self._job_status_locked(r)
                jobs[jid] = meta
            blob = pickle.dumps({
                "kv": {ns: dict(d) for ns, d in self._kv.items()},
                "named_actors": dict(self._named_actors),
                "actors": list(self._actors.values()),
                "pgs": list(self._pgs.values()),
                "jobs": jobs,
                # weight registry (metadata only — chunks live in their
                # producers' stores and survive a conductor bounce; a
                # forgotten registry could never send the gc/reap
                # notices producers' chunk lifetimes depend on)
                "weights": {
                    "committed": {n: dict(bv) for n, bv in
                                  self._weights_committed.items()},
                    "pending": [
                        {"name": n, "version": v,
                         "num_hosts": p["num_hosts"],
                         "run_id": p.get("run_id", ""),
                         "step": p.get("step"),
                         "fragments": dict(p["fragments"])}
                        for (n, v), p in self._weights_pending.items()],
                },
                # a restarted conductor mints a fresh head node id: PG
                # bundle assignments pointing at THIS id must be remapped
                "head_node_id": self._head_node_id,
            })
        tmp = self._persist_path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._persist_path)
        except OSError:
            with self._lock:
                self._dirty = True  # retry next monitor tick

    def _restore_state(self) -> None:
        """Load a prior snapshot from this session dir (conductor restart).
        Actor records come back with their worker addresses, so handles
        keep working against surviving worker processes; those workers'
        records are reconstructed provisionally and confirmed (pid filled
        in) by their periodic re-registration."""
        import pickle

        if not os.path.exists(self._persist_path):
            return
        try:
            with open(self._persist_path, "rb") as f:
                state = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError):
            return
        # Restore runs from __init__, before the serving threads
        # start, but the same records are later mutated under the
        # lock; take it here too so every mutation site is covered.
        with self._lock:
            head = self._nodes[self._head_node_id]
            self._kv = {ns: dict(d) for ns, d in state.get("kv", {}).items()}
            self._named_actors = dict(state.get("named_actors", {}))
            now = time.monotonic()
            # PGs first: live actors scheduled inside one hold the PG's
            # synthetic `_pg_<id>_<k>` keys, which must exist to re-charge.
            # Head-assigned bundles re-reserve now; bundles assigned to agent
            # nodes re-reserve when their node re-registers
            # (_reapply_pg_reservations from register_node).
            old_head = state.get("head_node_id")
            for pg in state.get("pgs", []):
                if pg.state != "CREATED":
                    continue
                if not getattr(pg, "assignments", None):
                    pg.assignments = [self._head_node_id] * len(pg.bundles)
                else:
                    pg.assignments = [
                        self._head_node_id if nid == old_head else nid
                        for nid in pg.assignments]
                for b, nid in zip(pg.bundles, pg.assignments):
                    if nid != self._head_node_id:
                        continue
                    self._acquire_resources(head, b)
                    for k, v in b.items():
                        pk = f"_pg_{pg.pg_id}_{k}"
                        head.total[pk] = head.total.get(pk, 0) + v
                        head.available[pk] = head.available.get(pk, 0) + v
                self._pgs[pg.pg_id] = pg
            for rec in state.get("actors", []):
                self._actors[rec.actor_id] = rec
                if rec.state in ("ALIVE", "RESTARTING") and rec.worker_id:
                    # mirror lease_worker: a PG-scheduled actor's lease holds
                    # the bundle's prefixed keys, NOT head general capacity
                    if rec.placement_group_id:
                        held = {f"_pg_{rec.placement_group_id}_{k}": v
                                for k, v in rec.resources.items()}
                    else:
                        held = dict(rec.resources)
                    w = WorkerRecord(worker_id=rec.worker_id,
                                     node_id=self._head_node_id,
                                     address=rec.address, state="ACTOR",
                                     resources=held,
                                     lease_node_id=self._head_node_id,
                                     restored_at=now)
                    self._workers[w.worker_id] = w
                    self._acquire_resources(head, held)
            wstate = state.get("weights") or {}
            self._weights_committed = {
                n: {int(v): m for v, m in bv.items()}
                for n, bv in (wstate.get("committed") or {}).items()}
            for p in wstate.get("pending") or []:
                # fresh TTL clock: `started` is monotonic and does not
                # survive a restart; the reaper ages them out from now
                self._weights_pending[(p["name"], int(p["version"]))] = {
                    "fragments": dict(p["fragments"]),
                    "num_hosts": int(p["num_hosts"]),
                    "run_id": p.get("run_id", ""), "step": p.get("step"),
                    "started": now}
            for jid, meta in state.get("jobs", {}).items():
                meta = dict(meta, proc=None)
                if meta.get("status") == "RUNNING":
                    # the job driver was orphaned by the crash; we can no
                    # longer supervise it
                    meta["status"] = "FAILED"
                    meta["end_time"] = meta.get("end_time") or time.time()
                self._jobs[jid] = meta

    # --------------------------------------------------------------- monitor

    def _monitor_loop(self) -> None:
        """Reap dead worker processes; restart actors; detect dead agent
        nodes by heartbeat age (reference gcs_health_check_manager.cc +
        gcs_actor_manager worker-death path)."""
        from .config import config

        node_timeout = config.node_timeout
        restore_grace = config.restore_grace
        last_mem_check = 0.0
        while not self._stopped:
            time.sleep(0.2)
            self._flush_state()
            try:
                # partial weight publishes (producer died mid-publish)
                # age out of the registry here
                self.weights_reap()
            except Exception:  # noqa: BLE001 — monitor must not die
                pass
            refresh_ms = config.memory_monitor_refresh_ms
            if refresh_ms > 0 and \
                    time.monotonic() - last_mem_check >= refresh_ms / 1000.0:
                last_mem_check = time.monotonic()
                try:
                    self._maybe_oom_kill()
                except Exception:  # noqa: BLE001 — monitor must not kill
                    pass           # the reap loop
            dead: List[WorkerRecord] = []
            with self._cv:
                agent_nodes = {nid for nid, n in self._nodes.items()
                               if n.has_agent}
                for w in self._workers.values():
                    if w.state == "DEAD":
                        continue
                    alive = True
                    if w.restored_at is not None:
                        # snapshot-restored record: presumed alive until
                        # the re-register window passes with no announce
                        alive = (time.monotonic() - w.restored_at
                                 <= restore_grace)
                    elif w.proc is not None:
                        alive = w.proc.poll() is None
                    elif w.node_id in agent_nodes:
                        # remote pid: liveness arrives via the agent's
                        # heartbeat (node_heartbeat dead_worker_ids)
                        alive = self._nodes[w.node_id].alive
                    elif w.pid is not None:
                        try:
                            os.kill(w.pid, 0)
                        except OSError:
                            alive = False
                    if not alive:
                        w.state = "DEAD"
                        self._release_resources(self._lease_release_node(w),
                                                w.resources)
                        w.resources = {}
                        self._free_worker_chips(w)
                        dead.append(w)
                        if w.address:
                            self._clients.invalidate(w.address)
                # heartbeat-expired agent nodes: mark dead, free resources
                now = time.monotonic()
                for n in self._nodes.values():
                    if (n.has_agent and n.alive
                            and now - n.last_heartbeat > node_timeout):
                        n.alive = False
                self._notify_all_locked()
            for w in dead:
                self._on_worker_death(w)

    def _maybe_oom_kill(self) -> None:
        """Memory-monitor tick (reference memory_monitor.h:52 +
        worker_killing_policy.cc): above the threshold, SIGKILL the
        greediest LOCAL worker — task workers before actors before idle —
        recording 'oom: ...' as its death cause so the submitter raises
        OutOfMemoryError instead of a bare crash. Remote nodes police
        themselves (node agent) and report causes via heartbeat."""
        from .config import config
        from .memory_monitor import MemoryMonitor

        threshold = config.memory_usage_threshold
        mon = getattr(self, "_mem_monitor", None)
        if mon is None or mon.threshold != threshold:
            mon = MemoryMonitor(threshold)
            self._mem_monitor = mon
        with self._lock:
            cands = [(w.worker_id, w.proc.pid, w.state)
                     for w in self._workers.values()
                     if w.proc is not None and w.proc.poll() is None]
        res = mon.kill_greediest(cands, "head")
        if res is None:
            return
        worker_id, cause = res
        with self._lock:
            rec = self._workers.get(worker_id)
            if rec is not None:
                rec.death_cause = cause  # submitters re-query after a
                # short grace, covering the kill→record window

    def worker_death_cause(self, worker_id: str) -> Optional[str]:
        with self._lock:
            w = self._workers.get(worker_id)
            return w.death_cause if w is not None else None

    def _on_worker_death(self, w: WorkerRecord) -> None:
        if not w.expected_death:
            # unexpected death (crash, OOM, chaos kill, host loss):
            # charge the host's failure domain and log the event —
            # this is what eventually quarantines a flaky host
            self._record_failure(w.lease_node_id or w.node_id,
                                 "worker_death",
                                 detail=w.death_cause or "",
                                 worker_id=w.worker_id)
        restart: List[str] = []
        with self._cv:
            for rec in self._actors.values():
                if rec.worker_id == w.worker_id and rec.state == "ALIVE":
                    if rec.restarts_remaining != 0:
                        if rec.restarts_remaining > 0:
                            rec.restarts_remaining -= 1
                        rec.state = "RESTARTING"
                        rec.num_restarts += 1
                        restart.append(rec.actor_id)
                    else:
                        rec.state = "DEAD"
                        rec.death_cause = "worker process died"
            self._dirty = True
            self._notify_all_locked()
        for actor_id in restart:
            self.publish("actor_state",
                         {"actor_id": actor_id, "state": "RESTARTING"})
            threading.Thread(target=self._place_actor, args=(actor_id,),
                             daemon=True).start()
        for rec in list(self._actors.values()):
            if rec.state == "DEAD" and rec.worker_id == w.worker_id:
                self.publish("actor_state",
                             {"actor_id": rec.actor_id, "state": "DEAD"})

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            workers = list(self._workers.values())
            jobs = list(getattr(self, "_jobs", {}).values())
            agents = [n.address for n in self._nodes.values()
                      if n.has_agent and n.alive]
            self._notify_all_locked()
        for addr in agents:
            try:
                self._clients.get(addr).call("stop_node", timeout=5.0)
            except Exception:
                pass
        for rec in jobs:
            if rec["proc"] is not None and rec["proc"].poll() is None:
                try:
                    os.killpg(rec["proc"].pid, signal.SIGTERM)
                except (OSError, ProcessLookupError):
                    pass
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                # RPC first: an rpc-handler thread can os._exit without
                # waiting for the MAIN thread to notice a signal flag —
                # on a contended 1-core host, SIGTERM-only teardown of
                # fork-server workers measured ~1.7s (the signal lands
                # on a non-main thread and the main thread must be
                # scheduled before the handler runs)
                if w.address:
                    try:
                        self._clients.get(tuple(w.address)).notify(
                            "shutdown_worker")
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                try:
                    w.proc.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 3.0
        for w in workers:
            if w.proc is not None:
                try:
                    w.proc.wait(max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    try:
                        w.proc.kill()
                        # reap: an unreaped zombie still passes the
                        # sweeper's os.kill(pid, 0) liveness probe, so
                        # its leaked segments would be skipped
                        w.proc.wait(2.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
        self._clients.close_all()
        self._flush_state()
        from .worker_spawn import stop_fork_server

        stop_fork_server(self._session_dir)
        # workers that needed SIGKILL leaked their shm arena segments
        from .object_store import cleanup_leaked_segments

        cleanup_leaked_segments()


class Conductor:
    """Hosts a ConductorHandler on an RpcServer (in-process head or
    standalone via conductor_main)."""

    def __init__(self, resources: Dict[str, float], session_dir: str,
                 host: str = "127.0.0.1", port: int = 0,
                 worker_env: Optional[Dict[str, str]] = None):
        self.handler = ConductorHandler(resources, session_dir,
                                        worker_env=worker_env)
        self.server = RpcServer(self.handler, host=host, port=port,
                                max_workers=32, warn_slow=True)
        self.handler.address = self.server.address
        self.handler._rpc_server = self.server

    def start(self) -> "Conductor":
        self.server.start()
        self.handler._monitor.start()
        # head-node log tailer: worker prints ride the worker_logs pubsub
        # channel to subscribed drivers (reference log_monitor.py)
        from .log_monitor import LogMonitor

        self._log_monitor = LogMonitor(
            os.path.join(self.handler._session_dir, "logs"),
            lambda batch: self.handler.publish("worker_logs", batch),
            node_label="head").start()
        # head-node preemption watcher: the maintenance-event channel
        # (RAY_TPU_MAINTENANCE_EVENT file) covers the head host too
        self._preemption_watcher = None
        from ray_tpu.resilience.preemption import (ENV_VAR,
                                                   PreemptionWatcher)

        if os.environ.get(ENV_VAR):
            h = self.handler
            self._preemption_watcher = PreemptionWatcher(
                lambda ev: h.report_preemption(
                    node_id=h._head_node_id, grace_s=ev.grace_s,
                    reason=ev.reason)).start()
        return self

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def stop(self) -> None:
        if getattr(self, "_preemption_watcher", None) is not None:
            self._preemption_watcher.stop()
        self.handler.stop()
        self.server.stop()
