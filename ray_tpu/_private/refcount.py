"""Distributed reference counting — automatic object lifetime.

The ownership-model analog of the reference's ReferenceCounter
(/root/reference/src/ray/core_worker/reference_count.h:61): the process
that created an object (its OWNER) decides when it can be freed, using

  live = local_handles > 0        (ObjectRef instances in the owner)
       or wire > 0                (sender-held pins while a ref rides
                                   inside a task/actor call, released by
                                   the SAME sender at reply time)
       or borrowers != {}         (remote processes holding handles)
       or result still pending    (producing task hasn't finished)

Every process instance-counts its ObjectRef handles (`__init__`/`__del__`
hooks). Non-owner processes register themselves as borrowers with the
owner on their first handle for an id and deregister on the last drop.

Wire pins are SENDER-balanced: the submitter increfs when a ref rides
into call args and decrefs when the call's reply arrives (by which time
the receiver has unpickled its handles and enqueued its borrower
registration). Incref and decref travel on the same ordered channel from
the same process, so the pin accounting can never go out of balance —
unlike receiver-balanced schemes, where an adopt can outrun the matching
incref and a clamped decrement silently strands the count. The remaining
cross-channel race (sender's decref+drop arriving just before the
receiver's adopt, both flushed on independent ~100ms timers) is closed
by a grace period: owner-side frees are scheduled and re-verified
_FREE_GRACE_S later rather than executed instantly.

All messages are batched and sent asynchronously off a flusher thread:
`__del__` never blocks on an RPC.

On owner-zero the owner deletes its store entry (including any spill
file), forgets lineage, and pushes `free_objects` to the recorded holder
(large results executed elsewhere) and any lingering borrower caches.

Known limits (deliberate, documented): refs serialized out-of-band (into
the conductor KV, files, …) are invisible to the tracker — like the
reference, such refs need the user to keep a live handle. Refs hidden
inside opaque user objects in call args miss the wire pin (collect_refs
walks plain containers only) but still get borrower accounting when the
receiver unpickles them. A sender dying before its reply leaks its pin —
the object stays alive, never freed prematurely.
"""
from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Set, Tuple

_FLUSH_PERIOD_S = 0.1
_FREE_GRACE_S = 0.3


class ReferenceTracker:
    """Per-process refcount state; one instance, attached to the Worker."""

    def __init__(self):
        # RLock: ObjectRef.__del__ can run inside ANY allocation (cyclic
        # GC), including one under this lock — a plain Lock would
        # self-deadlock on the nested untrack()
        self._lock = threading.RLock()
        # every process: live ObjectRef instances per id
        self._handles: Dict[str, int] = defaultdict(int)
        self._owner_of: Dict[str, Optional[Tuple[str, int]]] = {}
        # owner-side accounting for ids we own
        self._wire: Dict[str, int] = defaultdict(int)
        self._borrowers: Dict[str, Set[Tuple[str, int]]] = defaultdict(set)
        # ids freed while their producing task was still pending
        self._dead_pending: Set[str] = set()
        # owner-side: frees awaiting their grace re-check, oid -> due time
        self._free_due: Dict[str, float] = {}
        # outbox: owner addr -> list of (kind, object_id)
        self._outbox: Dict[Tuple[str, int], List[Tuple[str, str]]] = \
            defaultdict(list)
        self._worker = None  # set by attach()
        self._alive = True
        self._flusher: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def attach(self, worker) -> None:
        with self._lock:
            self._worker = worker
        if self._flusher is None:
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True, name="refcount-flush")
            self._flusher.start()

    def detach(self) -> None:
        """Called at worker shutdown: stop emitting RPCs, keep counting
        no-ops so late __del__s are harmless."""
        with self._lock:
            self._worker = None
            self._outbox.clear()
            self._free_due.clear()

    def _my_addr(self) -> Optional[Tuple[str, int]]:
        w = self._worker
        return tuple(w.address) if w is not None else None

    # ----------------------------------------------------- handle tracking

    def track(self, object_id: str, owner: Optional[Tuple[str, int]]) -> None:
        """An ObjectRef instance materialized in this process; the first
        foreign-owned one registers us as a borrower."""
        if not self._alive:
            return
        with self._lock:
            n = self._handles[object_id] = self._handles[object_id] + 1
            if owner is not None:
                self._owner_of.setdefault(object_id, tuple(owner))
            if n == 1:
                owner_addr = self._owner_of.get(object_id)
                me = self._my_addr()
                if owner_addr is not None and me is not None \
                        and tuple(owner_addr) != me:
                    self._outbox[tuple(owner_addr)].append(
                        ("adopt", object_id))

    def untrack(self, object_id: str) -> None:
        """An ObjectRef instance was garbage-collected."""
        if not self._alive:
            return
        free_oid = drop_cache = None
        with self._lock:
            n = self._handles.get(object_id)
            if n is None:
                return
            n -= 1
            if n > 0:
                self._handles[object_id] = n
                return
            del self._handles[object_id]
            owner_addr = self._owner_of.pop(object_id, None)
            me = self._my_addr()
            if me is None:
                return
            if owner_addr is not None and tuple(owner_addr) != me:
                # last local handle on a borrowed ref: tell the owner and
                # release our CACHE copy (see below, outside the lock)
                self._outbox[tuple(owner_addr)].append(("drop", object_id))
                drop_cache = object_id
            else:
                free_oid = object_id
        # Store calls happen OUTSIDE the tracker lock: a thread inside a
        # store method (holding its cv) can hit cyclic GC running
        # ObjectRef.__del__ → untrack (tracker lock) — taking the cv here
        # while holding the tracker lock would be the ABBA half of that
        # deadlock. delete_cached (not delete): if this process EXECUTED
        # the producing task, its entry is the authoritative holder copy
        # the owner's locator points at, not a refetchable cache.
        if drop_cache is not None:
            w = self._worker
            if w is not None:
                try:
                    w.store.delete_cached(drop_cache)
                except Exception:  # noqa: BLE001 — GC must not raise
                    pass
        if free_oid is not None:
            self._maybe_free_owned(free_oid)

    # --------------------------------------------------- submitter-side

    def wire_incref(self, refs) -> None:
        """Refs are about to ride into task/actor call args: pin them at
        their owners until wire_decref at reply time."""
        if not refs or not self._alive:
            return
        me = self._my_addr()
        with self._lock:
            for r in refs:
                owner = r.owner and tuple(r.owner)
                if owner is None or owner == me:
                    self._wire[r.id] += 1  # we own it: local fast path
                else:
                    self._outbox[owner].append(("incref", r.id))

    def wire_decref(self, refs) -> None:
        """The call carrying these refs completed (reply arrived): the
        receiver has adopted its handles, release the in-flight pins."""
        if not refs or not self._alive:
            return
        me = self._my_addr()
        to_check = []
        with self._lock:
            for r in refs:
                owner = r.owner and tuple(r.owner)
                if owner is None or owner == me:
                    if self._wire.get(r.id, 0) > 0:
                        self._wire[r.id] -= 1
                    to_check.append(r.id)
                else:
                    self._outbox[owner].append(("decref", r.id))
        for oid in to_check:
            self._maybe_free_owned(oid)

    # ------------------------------------------------------- owner-side RPC

    def apply_remote(self, from_addr, entries: List[Tuple[str, str]]) -> None:
        """Batched borrower/sender messages arriving at the owner."""
        from_addr = tuple(from_addr)
        to_check: Set[str] = set()
        with self._lock:
            for kind, oid in entries:
                if kind == "incref":
                    self._wire[oid] += 1
                elif kind == "decref":
                    if self._wire.get(oid, 0) > 0:
                        self._wire[oid] -= 1
                    to_check.add(oid)
                elif kind == "adopt":
                    self._borrowers[oid].add(from_addr)
                    # a registered borrower supersedes any scheduled free
                    self._free_due.pop(oid, None)
                elif kind == "drop":
                    self._borrowers[oid].discard(from_addr)
                    to_check.add(oid)
        for oid in to_check:
            self._maybe_free_owned(oid)

    def on_result_recorded(self, object_id: str) -> None:
        """Owner: a pending task result landed; free it if every handle
        died while it was still in flight."""
        self._maybe_free_owned(object_id)

    # ------------------------------------------------------------- freeing

    def _owned_live(self, object_id: str) -> bool:
        # caller must hold the lock
        return (self._handles.get(object_id, 0) > 0
                or self._wire.get(object_id, 0) > 0
                or bool(self._borrowers.get(object_id)))

    def _maybe_free_owned(self, object_id: str) -> None:
        """Schedule a grace-delayed free if the object looks dead; the
        flusher finalizes after _FREE_GRACE_S with a re-check (closes the
        sender-decref-vs-receiver-adopt cross-channel race)."""
        w = self._worker
        if w is None:
            return
        with self._lock:
            if self._owned_live(object_id):
                self._free_due.pop(object_id, None)
                return
            self._free_due.setdefault(object_id,
                                      time.monotonic() + _FREE_GRACE_S)

    def _finalize_due_frees(self) -> None:
        w = self._worker
        if w is None:
            return
        now = time.monotonic()
        with self._lock:
            due = [oid for oid, t in self._free_due.items() if t <= now]
            for oid in due:
                del self._free_due[oid]
        for oid in due:
            with self._lock:
                if self._owned_live(oid):
                    continue
                self._wire.pop(oid, None)
                borrowers = self._borrowers.pop(oid, set())
            if w._is_pending_local(oid):
                # producing task still running: free when the result lands
                with self._lock:
                    self._dead_pending.add(oid)
                # re-check: if the result landed between the pending check
                # and the mark, _record_results consulted was_freed_pending
                # BEFORE we set it — nobody else will finish this free
                if not w._is_pending_local(oid):
                    with self._lock:
                        self._dead_pending.discard(oid)
                    self._free_now(w, oid, borrowers)
                continue
            with self._lock:
                self._dead_pending.discard(oid)
            self._free_now(w, oid, borrowers)

    def was_freed_pending(self, object_id: str) -> bool:
        with self._lock:
            return object_id in self._dead_pending

    def _free_now(self, w, object_id: str, borrowers) -> None:
        try:
            w.store.delete(object_id)  # also unlinks any spill file
        except Exception:  # noqa: BLE001
            pass
        with w._state_lock:
            holder = w._locators.pop(object_id, None)
            w._lineage.pop(object_id, None)
        targets = set(borrowers)
        if holder is not None:
            targets.add(tuple(holder))
        for addr in targets:
            try:
                w.clients.get(tuple(addr)).notify("free_objects", [object_id])
            except Exception:  # noqa: BLE001 — holder already gone
                pass

    # -------------------------------------------------------------- flusher

    def _flush_loop(self) -> None:
        while self._alive:
            time.sleep(_FLUSH_PERIOD_S)
            self.flush()

    def flush(self) -> None:
        """Send the outbox and finalize due frees (also called directly
        by tests to accelerate convergence)."""
        with self._lock:
            w = self._worker
            if w is None:
                return
            batches, self._outbox = dict(self._outbox), defaultdict(list)
            me = self._my_addr()
        for addr, entries in batches.items():
            try:
                w.clients.get(tuple(addr)).notify(
                    "refcount_update", me, entries)
            except Exception:  # noqa: BLE001 — owner gone: nothing to free
                pass
        self._finalize_due_frees()

    # ------------------------------------------------------------ debugging

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "tracked_handles": len(self._handles),
                "owned_with_wire": sum(1 for v in self._wire.values() if v),
                "owned_with_borrowers": sum(
                    1 for v in self._borrowers.values() if v),
                "dead_pending": len(self._dead_pending),
                "frees_scheduled": len(self._free_due),
            }


def collect_refs(args: tuple, kwargs: dict, max_items: int = 10_000,
                 max_depth: int = 8) -> list:
    """Every ObjectRef reachable through plain containers (list/tuple/
    dict/set) in task arguments — the wire-pin scan. Refs hidden inside
    opaque user objects are not seen here; they still get borrower
    accounting when the receiver unpickles them, just without the
    in-flight pin (reference_count.h covers those via serialization
    hooks; our tradeoff is documented in the module docstring)."""
    from .object_store import ObjectRef

    # iterative on purpose: a self-recursive closure is a reference CYCLE
    # (fn -> cell -> fn) that pins every scanned ObjectRef until a cyclic
    # GC pass — which silently delays borrow drops in idle workers
    out: list = []
    stack: list = [(args, 0), (kwargs, 0)]
    budget = max_items  # counts CONTAINERS, not leaves: a long list of
    #                     scalars must not exhaust the budget before a
    #                     trailing ObjectRef is reached (premature free)
    while stack:
        obj, depth = stack.pop()
        if isinstance(obj, ObjectRef):
            out.append(obj)
        elif depth < max_depth and budget > 0:
            if isinstance(obj, (list, tuple, set, frozenset)):
                budget -= 1
                stack.extend((item, depth + 1) for item in obj)
            elif isinstance(obj, dict):
                budget -= 1
                for k, v in obj.items():
                    stack.append((k, depth + 1))
                    stack.append((v, depth + 1))
    return out


tracker = ReferenceTracker()


def _interpreter_teardown_guard() -> None:
    tracker._alive = False


# During interpreter shutdown __del__ ordering is arbitrary; turn the
# tracker off before modules are torn down so late drops are no-ops.
import atexit  # noqa: E402

atexit.register(_interpreter_teardown_guard)
