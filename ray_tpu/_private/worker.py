"""Per-process runtime: driver connect, task submission, task execution.

This is the analog of the reference's core worker
(/root/reference/src/ray/core_worker/core_worker.cc — SubmitTask :2067,
CreateActor :2139, SubmitActorTask :2377, Put :1198, Get :1460) plus the
Python driver layer (python/ray/_private/worker.py — init :1214, get :2523,
put :2655, wait :2720). One `Worker` instance per process (`global_worker`),
in one of two modes:

- "driver": created by `ray_tpu.init()`; may also host the in-process
  Conductor when starting a new local cluster.
- "worker": created by worker_main in processes the conductor spawns; runs an
  RPC server accepting pushed tasks (reference: direct worker-to-worker task
  push, core_worker.proto PushTask) and actor instantiation.

Submission protocol (reference direct_task_transport.h:75 kept):
  submitter resolves ObjectRef deps → leases a worker from the conductor →
  pushes the task directly to the worker → stores inline results / locators →
  returns the lease. Lineage for retries is kept submitter-side
  (reference task_manager.h:208); lost large objects are reconstructed by
  re-executing the producing task (object_recovery_manager.cc semantics).
"""
from __future__ import annotations

import asyncio
import contextlib
import os
import queue
import threading
import time
import traceback
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import exceptions as exc
from . import serialization
from .ids import JobID, ObjectID, TaskID
from .object_store import LocalObjectStore, ObjectRef, shm_threshold
from .rpc import (ClientPool, ConnectionLost, ReconnectingClient,
                  RemoteError, RpcServer)

global_worker: Optional["Worker"] = None

DEFAULT_MAX_RETRIES = 3


def _lease_idle_ttl() -> float:
    from .config import config

    return config.lease_idle_ttl


def _fetch_chunk() -> int:
    """Chunk size for cross-host pulls (reference pull_manager.cc: 64MB).
    Read through the flag table at use time so _system_config overrides
    reach this process too, not only spawned children."""
    from .config import config

    return config.fetch_chunk


def _compute_machine_id() -> str:
    """Identity of this HOST (not process): shm handoff is only valid
    between processes that share it. RAY_TPU_FORCE_REMOTE_FETCH makes
    every process claim a distinct machine (tests exercise the cross-host
    chunked path on one box)."""
    if os.environ.get("RAY_TPU_FORCE_REMOTE_FETCH"):
        return f"forced-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    import socket as _socket

    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f"{_socket.gethostname()}/{f.read().strip()}"
    except OSError:
        return _socket.gethostname()


_MACHINE_ID = _compute_machine_id()


def _current_traceparent() -> Optional[str]:
    """Traceparent of the calling thread's active span, or None when
    tracing is off (the common case — keep the hot path import-free)."""
    if os.environ.get("RAY_TPU_TRACING") != "1":
        return None
    from ray_tpu.util import tracing

    return tracing.current_traceparent()


@dataclass
class TaskSpec:
    task_id: str
    name: str
    fn_bytes: bytes  # cloudpickled callable
    args: tuple
    kwargs: dict
    return_ids: List[str]
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = DEFAULT_MAX_RETRIES
    owner: Optional[Tuple[str, int]] = None
    placement_group_id: Optional[str] = None
    runtime_env: Optional[Dict[str, Any]] = None  # prepared (URIs staged)
    # DEFAULT = pack (head-first); SPREAD = emptiest node first
    # (reference scheduling_strategy on @ray.remote)
    scheduling_strategy: str = "DEFAULT"
    # W3C traceparent captured on the SUBMITTING thread (spans are
    # thread-local; the submit-pool thread that serializes the wire has no
    # active span) — reference tracing_helper.py propagation-in-TaskSpec
    traceparent: Optional[str] = None


def _top_level_refs(args: tuple, kwargs: dict) -> List[ObjectRef]:
    """Top-level ObjectRef deps only, matching the reference's dependency
    resolver (dependency_resolver.cc)."""
    deps = [a for a in args if isinstance(a, ObjectRef)]
    deps += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
    return deps


class Worker:
    def __init__(self, mode: str, conductor_address: Tuple[str, int],
                 session_dir: str, worker_id: Optional[str] = None):
        self.mode = mode
        self.worker_id = worker_id or uuid.uuid4().hex
        self.job_id = JobID().hex()
        self.session_dir = session_dir
        self.store = LocalObjectStore(
            spill_dir=os.path.join(session_dir, "spill", self.worker_id[:12]))
        self.clients = ClientPool()
        # reconnecting: survives a conductor restart (persistence story)
        self.conductor = ReconnectingClient(conductor_address,
                                            connect_retries=30)
        self.conductor_address = tuple(conductor_address)
        self.handler = WorkerHandler(self)
        self.server = RpcServer(self.handler, max_workers=32).start()
        self.address = self.server.address
        # Submit concurrency scaled to the host: on small hosts extra
        # submit threads only add GIL contention (1-core measurement:
        # 2 threads = 3.7k pipelined tasks/s, 16 threads = 1.6k/s), while
        # the floor of 4 keeps slots available for dep-waits (bounded,
        # see _wait_dep_ready) so one blocked chain can't serialize
        # independent submissions.
        self._submit_pool = ThreadPoolExecutor(
            max_workers=min(16, max(4, 4 * (os.cpu_count() or 1))),
            thread_name_prefix="task-submit")
        # Worker-lease reuse cache (reference: normal_task_submitter.cc
        # keeps granted leases and pipelines same-shape tasks onto them).
        # Going back to the conductor for every task measured 235 tasks/s
        # pipelined — 8x UNDER the serial round-trip rate — because each
        # task paid lease+return RPCs plus four cross-thread wakeups;
        # reusing the lease for the next queued spec makes the hot path
        # one direct push per task. Entries: shape key -> [(worker_id,
        # address, idle_since)]; a reaper returns leases idle > TTL so
        # other drivers are never starved for long.
        self._lease_cache: Dict[tuple, List[Tuple[str, Tuple[str, int],
                                                  float]]] = {}
        self._lease_cache_lock = threading.Lock()
        # recache handoff + single-fetcher election (see _acquire_lease)
        self._lease_cv = threading.Condition(self._lease_cache_lock)
        self._lease_fetching: Dict[tuple, bool] = {}
        self._lease_reaper_started = False
        # owner-side state
        self._lineage: Dict[str, TaskSpec] = {}   # object_id -> producing spec
        self._pending_ids: set = set()            # ids awaiting a local result
        self._locators: Dict[str, Tuple[str, int]] = {}  # large-result holders
        # return_id -> submit-pool Future: the watchdog signal. A future
        # that is done while its ids are still pending means the submit
        # thread vanished without recording results — that must surface as
        # an error, never a silent forever-wait.
        self._inflight: Dict[str, Future] = {}
        # cancellation (reference CoreWorker::CancelTask core_worker.cc):
        # owner-side cancelled ids + where each pending id is executing
        self._cancelled: set = set()
        self._executing_at: Dict[str, Tuple[str, int]] = {}
        # push-based readiness (reference: ownership-based object directory
        # callbacks, object_directory.cc subscriptions — waiters subscribe
        # once and the owner pushes, instead of the waiter polling RPCs)
        self._object_waiters: Dict[str, set] = {}  # owner: oid -> waiters
        self._remote_ready: set = set()            # waiter: pushed-ready ids
        self._subscribed: set = set()              # ids subscribed at owner
        # conductor pubsub fan-in: channel -> local callbacks
        self._pub_lock = threading.Lock()
        self._pub_handlers: Dict[str, list] = {}
        self._pub_channels: set = set()
        # local endpoints remote producers push stream_chunk frames at
        # (reference: streaming generator refs, task_manager ObjectRefStream)
        self._streams: Dict[str, "queue.Queue"] = {}
        # executor-side: return_id -> thread ident running it (for the
        # cooperative async-exception interrupt)
        self._exec_threads: Dict[str, int] = {}
        # executor threads currently blocked in get()/wait() with their
        # lease parked at the conductor
        self._blocked_idents: set = set()
        self._state_lock = threading.Lock()
        # per-caller actor-call send ordering: frames must hit the socket in
        # seqno order or the server's reorder buffer can adopt a too-high
        # base and stall (reference: sequential_actor_submit_queue.cc)
        self._send_seq: Dict[str, int] = {}
        self._send_cv = threading.Condition()
        self._actor_runtime: Optional["ActorRuntime"] = None
        self._shutdown = False
        self._task_events: List[Dict[str, Any]] = []
        self._task_events_lock = threading.Lock()
        threading.Thread(target=self._event_flush_loop, daemon=True,
                         name="task-event-flush").start()
        from . import refcount

        refcount.tracker.attach(self)
        if mode == "driver":
            self._maybe_mirror_worker_logs()

    def _maybe_mirror_worker_logs(self) -> None:
        """log_to_driver (reference log_monitor.py): print worker
        stdout/stderr lines arriving on the worker_logs channel to this
        driver's stderr."""
        from .config import config

        if not config.log_to_driver:
            return
        import sys

        from .log_monitor import format_log_line

        def on_lines(batch) -> None:
            try:
                for entry in batch:
                    sys.stderr.write(format_log_line(entry) + "\n")
                sys.stderr.flush()
            except Exception:  # noqa: BLE001 — closed stderr on teardown
                pass

        try:
            self.subscribe_channel("worker_logs", on_lines)
        except Exception:  # noqa: BLE001 — conductor not up yet (tests
            pass           # constructing a bare Worker)

    # ------------------------------------------------------------ put / get

    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed")
        ref = ObjectRef(locator=self.address, owner=self.address)
        self.store.put_value(ref.id, value)
        return ref

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for r in ref_list:
            remaining = None if deadline is None else deadline - time.monotonic()
            out.append(self._get_one(r, remaining))
        return out[0] if single else out

    def _get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        if self.store.contains(ref.id):  # fast path: no lease dance
            return self._load_local(ref)
        deadline = None if timeout is None else time.monotonic() + timeout
        attempts = 0
        with self._lease_released_while_blocked():
            while True:
                if self.store.contains(ref.id):
                    return self._load_local(ref)
                if self._is_pending_local(ref.id):
                    self._wait_result(ref.id, deadline)
                    continue
                try:
                    self._fetch(ref, deadline)
                    continue
                except (ConnectionLost, KeyError, FileNotFoundError,
                        exc.ObjectLostError) as e:
                    attempts += 1
                    if attempts > 1 + self._lineage_retries(ref.id) or \
                            not self._try_reconstruct(ref):
                        raise exc.ObjectLostError(
                            ref.id, f"fetch failed ({e}) and "
                            "reconstruction unavailable") from e

    @contextlib.contextmanager
    def _lease_released_while_blocked(self):
        """An EXECUTOR thread entering a blocking get()/wait() parks its
        lease at the conductor so the tasks it waits on can schedule
        (reference: raylet resource release for workers blocked in
        ray.get — without it, dependent tasks deadlock the moment they
        outnumber CPUs). No-op for drivers, non-executor threads, and
        nested blocking sections."""
        # actor workers hold no CPU lease (state ACTOR, resources ~0) —
        # the conductor would no-op, so skip the RPC pair entirely
        if self.mode != "worker" or self._actor_runtime is not None:
            yield
            return
        ident = threading.get_ident()
        with self._state_lock:
            hook = ident in self._exec_threads.values() \
                and ident not in self._blocked_idents
        if not hook:
            yield
            return
        try:
            # registration inside the try: an async-exc cancel landing
            # anywhere past this point unwinds through the finally, so
            # the ident can never leak (a leak would permanently disable
            # lease-parking for this pool thread)
            with self._state_lock:
                self._blocked_idents.add(ident)
            try:
                self.conductor.notify("worker_blocked", self.worker_id)
            except ConnectionLost:
                pass
            yield
        finally:
            while True:  # injection-proof teardown (cf. _pop_exec_threads)
                try:
                    with self._state_lock:
                        self._blocked_idents.discard(ident)
                    try:
                        self.conductor.notify("worker_unblocked",
                                              self.worker_id)
                    except ConnectionLost:
                        pass
                    break
                except exc.TaskCancelledError:
                    continue

    def _load_local(self, ref: ObjectRef) -> Any:
        value = self.store.get_local(ref.id)  # raises stored errors
        if isinstance(value, exc.RayTpuError):
            raise value
        return value

    def _is_pending_local(self, object_id: str) -> bool:
        with self._state_lock:
            return object_id in self._pending_ids

    def _wait_result(self, object_id: str, deadline: Optional[float]) -> None:
        """Block until the local store holds an entry for `object_id` OR the
        id is no longer pending (large results are recorded as remote
        locators, which never create a store entry — waiting on the store cv
        alone would hang forever; this was a real livelock when a result
        larger than the store cap came back spilled→locator). Raises
        GetTimeoutError at `deadline` while still unresolved."""
        while True:
            with self._state_lock:
                pending = object_id in self._pending_ids
                fut = self._inflight.get(object_id)
            if not pending:
                return  # resolved out-of-store (locator) — caller fetches
            if fut is not None and fut.done():
                # watchdog: submit thread gone, id still pending — surface
                # an error rather than wait forever
                err = None
                try:
                    err = fut.exception(timeout=0)
                except BaseException as e2:  # noqa: BLE001 — incl. Cancelled
                    err = e2
                self.store.put_error(object_id, exc.TaskError(
                    err or SystemError("submit thread exited without "
                                       "recording results"),
                    "", "submit-watchdog"))
                with self._state_lock:
                    self._pending_ids.discard(object_id)
                    self._cancelled.discard(object_id)
                    self._inflight.pop(object_id, None)
                self._notify_object_waiters([object_id])
                return
            rem = None if deadline is None else deadline - time.monotonic()
            if rem is not None and rem <= 0:
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {object_id[:12]}…")
            if self.store.wait_ready_once(
                    object_id, 0.2 if rem is None else min(0.2, rem)):
                return

    def _locator_of(self, object_id: str) -> Optional[Tuple[str, int]]:
        with self._state_lock:
            return self._locators.get(object_id)

    def _fetch(self, ref: ObjectRef, deadline: Optional[float]) -> None:
        """Pull a value from its holder; fall back to asking the owner."""
        addr = self._locator_of(ref.id) or ref.locator
        if addr is not None and tuple(addr) != self.address:
            try:
                reply = self.clients.get(tuple(addr)).call(
                    "fetch_object", ref.id, _MACHINE_ID, timeout=60.0)
                self._consume_fetch_reply(ref.id, reply, tuple(addr))
                return
            except (ConnectionLost, RemoteError) as e:
                if isinstance(e, RemoteError) and not isinstance(
                        e.cause, (KeyError, FileNotFoundError)):
                    raise
                # holder gone or evicted: ask the owner below
        owner = ref.owner
        if owner is None or tuple(owner) == self.address:
            raise exc.ObjectLostError(ref.id, "no live holder and no owner")
        rem = None if deadline is None else max(0.1, deadline - time.monotonic())
        kind, payload = self.clients.get(tuple(owner)).call(
            "resolve_object", ref.id, _MACHINE_ID, timeout=rem)
        if kind == "locator":
            addr = tuple(payload)
            reply = self.clients.get(addr).call(
                "fetch_object", ref.id, _MACHINE_ID, timeout=60.0)
            self._consume_fetch_reply(ref.id, reply, addr)
        else:
            self._consume_fetch_reply(ref.id, (kind, payload), tuple(owner))

    def _consume_fetch_reply(self, object_id: str, reply,
                             src_addr: Tuple[str, int]) -> None:
        """Handle a fetch_object/resolve_object reply; 'stream' replies
        are pulled down in bounded chunks (reference pull_manager.cc — a
        multi-GB object must never ride one RPC frame)."""
        kind, payload = reply
        if kind != "stream":
            self._store_fetched(object_id, kind, payload)
            return
        meta, total, sizes = payload
        data = bytearray(total)
        client = self.clients.get(src_addr)
        pos = 0
        while pos < total:
            n = min(_fetch_chunk(), total - pos)
            chunk = client.call("fetch_object_range", object_id, pos, n,
                                timeout=60.0)
            data[pos:pos + len(chunk)] = chunk
            if not chunk:
                raise exc.ObjectLostError(object_id,
                                          "holder returned empty chunk")
            pos += len(chunk)
        views, off = [], 0
        mv = memoryview(data)
        for s in sizes:
            views.append(mv[off:off + s])
            off += s
        self.store.put_serialized(object_id, meta, views, copy=False)

    def _store_fetched(self, object_id: str, kind: str, payload) -> None:
        if kind == "inline":
            meta, bufs = payload
            self.store.put_serialized(object_id, meta,
                                      [memoryview(b) for b in bufs])
        elif kind == "shm":
            meta, shm_name, layout = payload
            self.store.put_shm_reference(object_id, meta, shm_name, layout)
        elif kind == "error":
            raise payload if isinstance(payload, exc.RayTpuError) else \
                exc.ObjectLostError(object_id, str(payload))
        else:
            raise ValueError(f"bad fetch kind {kind}")

    def _lineage_retries(self, object_id: str) -> int:
        with self._state_lock:
            spec = self._lineage.get(object_id)
        return spec.max_retries if spec is not None else 0

    def _try_reconstruct(self, ref: ObjectRef) -> bool:
        """Re-execute the producing task (lineage reconstruction)."""
        with self._state_lock:
            spec = self._lineage.get(ref.id)
            if spec is None or spec.max_retries <= 0:
                return False
            spec.max_retries -= 1
            for oid in spec.return_ids:
                self._locators.pop(oid, None)
                self._pending_ids.add(oid)
        # the resubmission's _submit_and_record will decref on completion:
        # re-pin the args so the pair stays balanced
        from . import refcount

        refcount.tracker.wire_incref(
            refcount.collect_refs(spec.args, spec.kwargs))
        for oid in spec.return_ids:
            self.store.invalidate(oid)
        self._register_inflight(
            spec.return_ids, self._submit_pool.submit(
                self._submit_and_record, spec))
        return True

    def _register_inflight(self, return_ids: List[str], fut: Future) -> None:
        with self._state_lock:
            for oid in return_ids:
                if oid in self._pending_ids:  # may already have completed
                    self._inflight[oid] = fut

    # -------------------------------------------------------------- wait

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        refs = list(refs)
        seen = set()
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError("wait() expects ObjectRefs")
            if r.id in seen:
                raise ValueError("wait() requires distinct refs")
            seen.add(r.id)
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        # Push-driven: each remote ref costs at most ONE subscribe_object
        # RPC; after that the owner pushes object_available and readiness
        # checks are purely local. The bounded wait_change handles lost
        # wakes; the ~5s re-subscribe heals lost PUSHES (owner's notify hit
        # a transient connection drop after it already forgot the waiter,
        # or the owner restarted) — without it a single failed push would
        # wedge this waiter forever.
        # fast path first: enough already-ready refs (or a zero timeout)
        # must not pay the lease park/unpark RPC pair — polling loops
        # call wait(timeout=0) hot
        ready_ids: set = {r.id for r in refs if self._ref_ready(r)}
        idle_cycles = 0
        if len(ready_ids) >= num_returns or (
                deadline is not None and time.monotonic() >= deadline):
            return self._wait_split(refs, ready_ids, num_returns)
        with self._lease_released_while_blocked():
            while True:
                progressed = False
                for r in refs:
                    if r.id not in ready_ids and self._ref_ready(r):
                        ready_ids.add(r.id)
                        progressed = True
                if len(ready_ids) >= num_returns or (
                        deadline is not None
                        and time.monotonic() >= deadline):
                    break
                idle_cycles = 0 if progressed else idle_cycles + 1
                if idle_cycles >= 20:  # ~5s of silence: re-probe owners
                    idle_cycles = 0
                    with self._state_lock:
                        for r in refs:
                            if r.id not in ready_ids:
                                self._subscribed.discard(r.id)
                rem = None if deadline is None \
                    else deadline - time.monotonic()
                self.store.wait_change(
                    0.25 if rem is None else max(0.0, min(0.25, rem)))
        return self._wait_split(refs, ready_ids, num_returns)

    @staticmethod
    def _wait_split(refs, ready_ids: set, num_returns: int):
        ready = [r for r in refs if r.id in ready_ids]
        ready = ready[:num_returns]
        # preserve original order among not_ready (extra ready refs stay)
        not_ready = [r for r in refs
                     if r.id not in {x.id for x in ready}]
        return ready, not_ready

    def _ref_ready(self, ref: ObjectRef) -> bool:
        if self.store.contains(ref.id) or self._locator_of(ref.id) is not None:
            with self._state_lock:  # value landed locally: drop push state
                self._remote_ready.discard(ref.id)
                self._subscribed.discard(ref.id)
            return True
        with self._state_lock:
            if ref.id in self._remote_ready:
                return True
        if self._is_pending_local(ref.id):
            return False
        owner = ref.owner
        if owner is None or tuple(owner) == self.address:
            return False
        with self._state_lock:
            if ref.id in self._subscribed:
                return False  # owner's push will wake the store cv
            self._subscribed.add(ref.id)
        try:
            ready = bool(self.clients.get(tuple(owner)).call(
                "subscribe_object", ref.id, self.address, timeout=5.0))
        except (ConnectionLost, RemoteError, TimeoutError):
            # TimeoutError too: a GIL-bound owner answering late must not
            # leave ref.id wedged in _subscribed with no push coming
            with self._state_lock:
                self._subscribed.discard(ref.id)
            return False
        if ready:
            with self._state_lock:
                self._remote_ready.add(ref.id)
        return ready

    def _notify_object_waiters(self, object_ids: Sequence[str]) -> None:
        """Owner-side: push readiness (value OR error recorded) to every
        wait() subscriber of these ids, then forget them."""
        targets: Dict[Tuple[str, int], List[str]] = {}
        with self._state_lock:
            for oid in object_ids:
                for addr in self._object_waiters.pop(oid, ()):
                    targets.setdefault(addr, []).append(oid)
        for addr, oids in targets.items():
            try:
                self.clients.get(addr).notify("object_available", oids)
            except Exception:  # noqa: BLE001 — waiter gone: nothing to wake
                pass

    # -------------------------------------------------------- task submission

    def submit_task(self, fn, args: tuple, kwargs: dict, *,
                    name: str = "", num_returns: int = 1,
                    resources: Optional[Dict[str, float]] = None,
                    max_retries: int = DEFAULT_MAX_RETRIES,
                    placement_group_id: Optional[str] = None,
                    runtime_env: Optional[Dict[str, Any]] = None,
                    scheduling_strategy: str = "DEFAULT",
                    fn_bytes: Optional[bytes] = None):
        if runtime_env:
            from . import runtime_env as renv

            runtime_env = renv.prepare(self.conductor, runtime_env)
        return_ids = [ObjectID().hex() for _ in range(num_returns)]
        spec = TaskSpec(
            task_id=TaskID().hex(),
            name=name or getattr(fn, "__name__", "task"),
            fn_bytes=fn_bytes if fn_bytes is not None
            else serialization.dumps(fn),
            args=args, kwargs=kwargs,
            return_ids=return_ids,
            resources=dict(resources or {}),
            max_retries=max_retries,
            owner=self.address,
            placement_group_id=placement_group_id,
            runtime_env=runtime_env,
            scheduling_strategy=scheduling_strategy,
            traceparent=_current_traceparent())
        refs = [ObjectRef(oid, locator=None, owner=self.address)
                for oid in return_ids]
        from . import refcount

        refcount.tracker.wire_incref(refcount.collect_refs(args, kwargs))
        with self._state_lock:
            for oid in return_ids:
                self._lineage[oid] = spec
                self._pending_ids.add(oid)
        self._register_inflight(
            return_ids, self._submit_pool.submit(
                self._submit_and_record, spec))
        return refs[0] if num_returns == 1 else refs

    def _submit_and_record(self, spec: TaskSpec) -> None:
        """Submitter thread: resolve deps → lease → push → record results.
        Retries on worker crash up to spec.max_retries."""
        try:
            retries = spec.max_retries
            while True:
                try:
                    self._submit_once(spec)
                    return
                except (ConnectionLost, exc.WorkerCrashedError):
                    if retries <= 0:
                        raise
                    retries -= 1
        except BaseException as e:  # noqa: BLE001 — deliver to waiters
            if isinstance(e, RemoteError) and isinstance(
                    e.cause, exc.RayTpuError):
                # e.g. SchedulingError from a hard NodeAffinity lease —
                # surface the typed error, not an opaque RPC wrapper
                e = e.cause
            err = e if isinstance(e, exc.RayTpuError) else exc.TaskError(
                e, traceback.format_exc(), spec.name)
            for oid in spec.return_ids:
                self.store.put_error(oid, err)
            with self._state_lock:
                self._pending_ids.difference_update(spec.return_ids)
                self._cancelled.difference_update(spec.return_ids)
                for oid in spec.return_ids:
                    self._inflight.pop(oid, None)
            self._notify_object_waiters(spec.return_ids)
            # infrastructure failures (worker crash, lease failure) must
            # show up in `summary`/`timeline` as FAILED too — but a cancel
            # that aborted the submit thread is CANCELLED, same as one
            # landing post-push
            now = time.time()
            status = "CANCELLED" if isinstance(e, exc.TaskCancelledError) \
                else "FAILED"
            self._record_event(spec, now, None, status)
        finally:
            # release the in-flight pins taken at submission — success or
            # failure, the receiver's adoption window has closed
            from . import refcount

            refcount.tracker.wire_decref(
                refcount.collect_refs(spec.args, spec.kwargs))

    def _is_cancelled(self, return_ids) -> bool:
        with self._state_lock:
            return any(oid in self._cancelled for oid in return_ids)

    # ------------------------------------------------- worker-lease reuse

    def _lease_key(self, spec: TaskSpec) -> tuple:
        """Cache key under which a granted lease is reusable: same
        resource shape, placement group, and scheduling strategy. The
        runtime env rides in the pushed spec (workers apply it per task),
        so it does not partition the cache."""
        strat = spec.scheduling_strategy
        if isinstance(strat, (tuple, list)):
            strat = tuple(strat)
        return (tuple(sorted(spec.resources.items())),
                spec.placement_group_id, strat)

    @staticmethod
    def _lease_cacheable(key: tuple) -> bool:
        """SPREAD tasks must get a FRESH placement decision per task
        (emptiest node — reference spread_scheduling_policy.cc); reusing
        a cached lease would pack consecutive tasks onto whichever node
        answered first. Everything else (DEFAULT pack, PG bundles,
        NodeAffinity pins) is placement-stable and safe to reuse."""
        return key[2] != "SPREAD"

    def _lease_take_cached(self, key: tuple):
        with self._lease_cache_lock:
            entries = self._lease_cache.get(key)
            if entries:
                worker_id, address, _ = entries.pop()
                return worker_id, address
        return None

    def _acquire_lease(self, key: tuple, spec: TaskSpec,
                       deps) -> Tuple[str, Tuple[str, int]]:
        """Cached lease, or one fetched from the conductor — with at most
        ONE thread per shape parked in the conductor's lease_worker at a
        time. The rest wait locally on the cache condition, so a lease
        recached by a finishing push is handed to a waiter immediately.
        Without this, a burst's tail specs sat in threads parked at the
        conductor while every worker idled in the local cache, drained
        only by the reaper TTL (measured: last 8 tasks of a 300-task
        burst at ~25 tasks/s)."""
        from .config import config

        deadline = time.monotonic() + config.worker_start_timeout
        while True:
            with self._lease_cv:
                entries = self._lease_cache.get(key)
                if entries:
                    worker_id, address, _ = entries.pop()
                    return worker_id, address
                if not self._lease_fetching.get(key):
                    self._lease_fetching[key] = True
                    break  # elected fetcher: go to the conductor
                if self._shutdown:
                    raise exc.TaskCancelledError(spec.name)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no worker lease for {spec.name} within "
                        f"{config.worker_start_timeout:.0f}s")
                self._lease_cv.wait(min(0.05, remaining))
        try:
            return self.conductor.call(
                "lease_worker", spec.resources, spec.placement_group_id,
                None, spec.scheduling_strategy, self._arg_locations(deps),
                timeout=None)
        finally:
            with self._lease_cv:
                self._lease_fetching[key] = False
                self._lease_cv.notify_all()

    def _lease_recache(self, key: tuple, worker_id: str,
                       address: Tuple[str, int]) -> None:
        if self._shutdown or not self._lease_cacheable(key):
            try:
                self.conductor.notify("return_worker", worker_id)
            except ConnectionLost:
                pass
            return
        with self._lease_cv:
            self._lease_cache.setdefault(key, []).append(
                (worker_id, tuple(address), time.monotonic()))
            self._lease_cv.notify_all()
            start_reaper = not self._lease_reaper_started
            if start_reaper:
                self._lease_reaper_started = True
        if start_reaper:
            threading.Thread(target=self._lease_reaper_loop, daemon=True,
                             name="lease-reaper").start()

    def _lease_reaper_loop(self) -> None:
        """Return leases idle beyond the TTL so cached workers are only
        held while this driver is actively pipelining — other drivers'
        lease_worker calls see at most one TTL of extra wait."""
        ttl = _lease_idle_ttl()
        while not self._shutdown:
            time.sleep(min(0.05, ttl / 2))
            now = time.monotonic()
            expired = []
            with self._lease_cache_lock:
                for key in list(self._lease_cache):
                    keep = []
                    for wid, addr, t in self._lease_cache[key]:
                        if now - t > ttl:
                            expired.append(wid)
                        else:
                            keep.append((wid, addr, t))
                    if keep:
                        self._lease_cache[key] = keep
                    else:
                        del self._lease_cache[key]
            for wid in expired:
                try:
                    self.conductor.notify("return_worker", wid)
                except ConnectionLost:
                    # transient (reconnecting client): the conductor will
                    # reclaim the worker via its own liveness tracking —
                    # keep reaping, a dead reaper would pin future leases
                    pass

    def _return_all_cached_leases(self) -> None:
        with self._lease_cache_lock:
            entries = [wid for lst in self._lease_cache.values()
                       for wid, _, _ in lst]
            self._lease_cache.clear()
        for wid in entries:
            try:
                self.conductor.notify("return_worker", wid)
            except ConnectionLost:
                return

    def _submit_once(self, spec: TaskSpec) -> None:
        if self._is_cancelled(spec.return_ids):
            raise exc.TaskCancelledError(spec.name)
        deps = _top_level_refs(spec.args, spec.kwargs)
        for dep in deps:
            self._wait_dep_ready(
                dep,
                should_abort=lambda: self._is_cancelled(spec.return_ids))
        if self._is_cancelled(spec.return_ids):
            # cancelled during the dep wait: stop HERE — falling through
            # would park this submit slot in the unbounded lease_worker
            # wait, re-pinning the slot the bounded dep loop just freed
            raise exc.TaskCancelledError(spec.name)
        key = self._lease_key(spec)
        if self._lease_cacheable(key):
            worker_id, address = self._acquire_lease(key, spec, deps)
        else:
            worker_id, address = self.conductor.call(
                "lease_worker", spec.resources, spec.placement_group_id,
                None, spec.scheduling_strategy, self._arg_locations(deps),
                timeout=None)
        if self._is_cancelled(spec.return_ids):  # cancelled during lease
            self._lease_recache(key, worker_id, address)
            raise exc.TaskCancelledError(spec.name)
        with self._state_lock:
            for oid in spec.return_ids:
                self._executing_at[oid] = tuple(address)
        t0 = time.time()
        recache = True
        try:
            reply = self.clients.get(tuple(address)).call(
                "push_task", self._wire_spec(spec), timeout=None)
        except ConnectionLost as e:
            # worker gone (crash or force-cancel kill): the lease is dead
            # — release its resources at the conductor, never recache
            recache = False
            if self._is_cancelled(spec.return_ids):
                # force-cancel killed the worker mid-task: that is the
                # requested outcome, not a crash to retry
                raise exc.TaskCancelledError(spec.name) from e
            raise self._worker_crash_error(worker_id, spec.name) from e
        finally:
            with self._state_lock:
                for oid in spec.return_ids:
                    self._executing_at.pop(oid, None)
            if recache:
                self._lease_recache(key, worker_id, address)
            else:
                try:
                    self.conductor.notify("return_worker", worker_id)
                except ConnectionLost:
                    pass
        # record ALWAYS: cancelled ids are skipped inside (their caller
        # already holds TaskCancelledError) but sibling return values of a
        # multi-return task must still be delivered
        skipped = self._record_results(spec.return_ids, reply,
                                       holder=tuple(address))
        if skipped:
            self._record_event(spec, t0, tuple(address), "CANCELLED")
            return
        status = "FAILED" if any(entry[1] == "error" for entry in reply) \
            else "FINISHED"
        self._record_event(spec, t0, tuple(address), status)

    def _worker_crash_error(self, worker_id: str,
                            task_name: str) -> exc.WorkerCrashedError:
        """Typed error for a worker dying mid-task: the memory monitor
        records 'oom: ...' BEFORE killing locally, and agent OOM kills
        arrive with the next heartbeat — so query, once immediately and
        once after a short grace, before settling for a generic crash."""
        cause = None
        for delay in (0.0, 0.3):
            if delay:
                time.sleep(delay)
            try:
                cause = self.conductor.call("worker_death_cause", worker_id,
                                            timeout=5.0)
            except (ConnectionLost, TimeoutError, RemoteError):
                break
            if cause is not None:
                break
        if cause and cause.startswith("oom"):
            return exc.OutOfMemoryError(
                f"worker {worker_id[:12]}… was OOM-killed running "
                f"{task_name} — {cause}")
        return exc.WorkerCrashedError(
            f"worker {worker_id[:12]}… died running {task_name}"
            + (f" ({cause})" if cause else ""))

    def _wire_spec(self, spec: TaskSpec) -> dict:
        # "machine" tells the executor whether a shm-name result reply is
        # attachable by us (same host) or must come back as a locator we
        # fetch through the machine-id-aware chunked path
        return {"task_id": spec.task_id, "name": spec.name,
                "fn_bytes": spec.fn_bytes, "args": spec.args,
                "kwargs": spec.kwargs, "return_ids": spec.return_ids,
                "owner": spec.owner, "runtime_env": spec.runtime_env,
                "machine": _MACHINE_ID, "traceparent": spec.traceparent}

    def _record_results(self, return_ids: List[str], reply: list,
                        holder: Optional[Tuple[str, int]] = None) -> set:
        """Record a task/actor-call reply; returns the subset of ids that
        were cancelled (skipped — their caller already holds
        TaskCancelledError). Settles ALL ids: pending/inflight/cancelled
        bookkeeping is cleared whether cancelled or not."""
        with self._state_lock:
            cancelled = {oid for oid in return_ids if oid in self._cancelled}
        for oid, kind, payload in reply:
            if oid in cancelled:
                continue  # caller already holds TaskCancelledError
            if kind == "locator":
                with self._state_lock:
                    self._locators[oid] = tuple(payload)
            elif kind == "error":
                self.store.put_error(oid, payload)
            else:
                self._store_fetched(oid, kind, payload)
                if kind == "shm" and holder is not None:
                    # same-host large result: our entry is a zero-copy
                    # REFERENCE into the executor's memory — remember who
                    # holds the bytes so refcount-zero can free them (and
                    # so an evicted reference can refetch)
                    with self._state_lock:
                        self._locators[oid] = tuple(holder)
        with self._state_lock:
            self._pending_ids.difference_update(return_ids)
            self._cancelled.difference_update(return_ids)
            for oid in return_ids:
                self._inflight.pop(oid, None)
        # locator-only results create no store entry: wake waiters so
        # _wait_result re-checks the pending set and moves on to fetch
        self.store.notify_waiters()
        self._notify_object_waiters(return_ids)
        # results whose every handle died while the task was in flight
        # are freed right here (refcounting dead-pending path)
        from . import refcount

        for oid in return_ids:
            if refcount.tracker.was_freed_pending(oid):
                refcount.tracker.on_result_recorded(oid)
        return cancelled

    def _arg_locations(self, deps) -> Optional[List[Tuple[Tuple[str, int],
                                                          int]]]:
        """(holder_address, nbytes) per arg ref — the conductor's
        locality signal (reference core_worker/lease_policy.cc: lease
        from the raylet holding the most argument bytes). Size is 0 when
        only a remote locator is known (presence still counts)."""
        locs = []
        for dep in deps:
            addr = self._locator_of(dep.id) or dep.locator
            nbytes = self.store.size_of(dep.id)
            if addr is None and nbytes > 0:
                addr = self.address  # value lives in this process
            if addr is not None:
                locs.append((tuple(addr), int(nbytes)))
        return locs or None

    def _wait_dep_ready(self, ref: ObjectRef, should_abort=None) -> None:
        """Block until `ref`'s value exists somewhere reachable.

        Bounded wait + re-check: every blocking step caps at ~2s, so a
        submit-pool slot is never pinned by one unbounded RPC — with only
        16 submit threads, 16 tasks each waiting forever on a borrowed
        dep would stall all submission. Between steps the loop re-checks
        local state, shutdown, and `should_abort` (task cancellation).
        """
        while True:
            if self.store.contains(ref.id) or self._locator_of(ref.id):
                return
            if self._shutdown or (should_abort is not None
                                  and should_abort()):
                return
            if self._is_pending_local(ref.id):
                self.store.wait_ready(ref.id, 0.2)
                continue
            owner = ref.owner
            if owner is None or tuple(owner) == self.address:
                # nothing to wait on; executor fetch will surface errors
                return
            # owner-side wait bounded at 2s per round trip; False means
            # "still pending" — loop and re-check. A TimeoutError is
            # owner-side queueing (its handler pool is busy), not a task
            # failure: re-poll.
            try:
                if self.clients.get(tuple(owner)).call(
                        "resolve_object_location", ref.id, 2.0,
                        timeout=15.0):
                    return
            except TimeoutError:
                continue

    def _record_event(self, spec: TaskSpec, t0: float, address,
                      status: str = "FINISHED") -> None:
        self._record_event_raw(spec.task_id, spec.name, t0, address, status)

    def _record_event_raw(self, task_id: str, name: str, t0: float,
                          address, status: str) -> None:
        ev = {"task_id": task_id, "name": name, "start": t0,
              "end": time.time(),
              "worker": tuple(address) if address else None,
              "job_id": self.job_id, "status": status}
        with self._task_events_lock:
            self._task_events.append(ev)
            n = len(self._task_events)
        if n >= 50:
            self._flush_task_events()

    def _flush_task_events(self) -> None:
        """Push buffered events to the conductor (size-triggered above,
        time-triggered by the flusher thread — external consumers like
        the dashboard must see small workloads too; reference
        task_event_buffer.cc periodic flush)."""
        with self._task_events_lock:
            batch, self._task_events = self._task_events, []
        if batch:
            try:
                self.conductor.notify("report_task_events", batch)
            except ConnectionLost:
                pass
        from ray_tpu.util import envknobs

        if envknobs.get_str("RAY_TPU_TRACING") == "1":
            from ray_tpu.util import tracing

            spans = tracing.drain()
            if spans:
                try:
                    self.conductor.notify("report_spans", spans)
                except ConnectionLost:
                    pass

    def _event_flush_loop(self) -> None:
        while not self._shutdown:
            time.sleep(2.0)
            # re-check after the sleep: a stale flusher of a torn-down
            # Worker must not drain the process-global span buffer into
            # its dead conductor (drops the next cluster's spans)
            if not self._shutdown:
                self._flush_task_events()

    # ------------------------------------------------------------ execution

    def _load_task_fn(self, fn_bytes: bytes):
        """Deserialize a pushed task function, memoized on the exact
        byte string (the submitter serializes each RemoteFunction once,
        so repeat tasks arrive with identical bytes — reference:
        function_manager.py caches exported functions by descriptor).
        Bounded so a driver cycling many distinct functions cannot grow
        worker memory without limit."""
        cache = getattr(self, "_fn_cache", None)
        if cache is None:
            cache = self._fn_cache = {}
        fn = cache.get(fn_bytes)
        if fn is None:
            fn = serialization.loads(fn_bytes)
            if len(cache) >= 256:
                cache.clear()
            cache[fn_bytes] = fn
        return fn

    def execute_task(self, wire: dict) -> list:
        """Run a pushed task; return reply entries (reference:
        task_execution_handler _raylet.pyx:2247; returns stored per
        core_worker.cc:3268)."""
        name = wire.get("name", "task")
        ident = threading.get_ident()
        with self._state_lock:
            for oid in wire["return_ids"]:
                self._exec_threads[oid] = ident
        try:
            try:
                fn = self._load_task_fn(wire["fn_bytes"])
                args = tuple(self._materialize(a) for a in wire["args"])
                kwargs = {k: self._materialize(v)
                          for k, v in wire["kwargs"].items()}
                from . import runtime_env as renv

                with renv.applied(self.conductor, wire.get("runtime_env")):
                    if wire.get("traceparent"):
                        from ray_tpu.util import tracing

                        with tracing.span(f"task:{name}",
                                          traceparent=wire["traceparent"]):
                            result = fn(*args, **kwargs)
                    else:
                        result = fn(*args, **kwargs)
            except exc.TaskCancelledError as e:
                return [(oid, "error", e) for oid in wire["return_ids"]]
            except BaseException as e:  # noqa: BLE001
                err = exc.TaskError(e, traceback.format_exc(), name)
                return [(oid, "error", err) for oid in wire["return_ids"]]
            return_ids = wire["return_ids"]
            if len(return_ids) == 1:
                results = [result]
            else:
                results = list(result)
                if len(results) != len(return_ids):
                    err = exc.TaskError(
                        ValueError(
                            f"task {name} returned {len(results)} values, "
                            f"expected {len(return_ids)}"), "", name)
                    return [(oid, "error", err) for oid in return_ids]
            return [self._store_result(oid, value, wire.get("machine"))
                    for oid, value in zip(return_ids, results)]
        except exc.TaskCancelledError as e:
            # async-exc injection landed AFTER fn returned (teardown /
            # result-serialization window) — still a cancel, not a crash
            return [(oid, "error", e) for oid in wire["return_ids"]]
        finally:
            self._pop_exec_threads(wire["return_ids"])

    def _pop_exec_threads(self, return_ids, also=None) -> None:
        """Executor teardown that a pending async-exc injection must never
        skip: retry until the pops (and `also`, which must be idempotent)
        complete without interruption. Injection happens under _state_lock
        with an _exec_threads membership check, so once the pop lands no
        further injection can target this thread for these ids."""
        while True:
            try:
                with self._state_lock:
                    for oid in return_ids:
                        self._exec_threads.pop(oid, None)
                if also is not None:
                    also()
                break
            except exc.TaskCancelledError:
                continue

    def _materialize(self, v: Any) -> Any:
        return self._get_one(v, None) if isinstance(v, ObjectRef) else v

    def _store_result(self, oid: str, value: Any,
                      requester_machine: Optional[str] = None):
        try:
            nbytes = self.store.put_value(oid, value)
            meta, shm_name, layout, inline = self.store.export(oid)
        except BaseException as e:  # noqa: BLE001 — serialization failure
            return (oid, "error",
                    exc.TaskError(e, traceback.format_exc(), "store_result"))
        same_host = requester_machine is None \
            or requester_machine == _MACHINE_ID
        if shm_name is not None:
            if same_host:
                return (oid, "shm", (meta, shm_name, layout))
            # cross-host: a shm name is meaningless there — hand back a
            # locator; the caller pulls through the chunked fetch path
            return (oid, "locator", self.address)
        if nbytes <= shm_threshold():
            return (oid, "inline", (meta, inline))
        return (oid, "locator", self.address)

    # --------------------------------------------------------------- actors

    def create_actor(self, cls, args, kwargs, options: Dict[str, Any]) -> dict:
        if options.get("runtime_env"):
            from . import runtime_env as renv

            options = dict(options)
            options["runtime_env"] = renv.prepare(self.conductor,
                                                  options["runtime_env"])
        spec_bytes = serialization.dumps((cls, args, kwargs, dict(options)))
        resources = dict(options.get("resources") or {})
        num_cpus = options.get("num_cpus")
        # Reference semantics: actors default to 0 CPUs while running
        # (python/ray/_private/ray_option_utils.py) so idle actors don't
        # pin cluster CPUs — this is what makes 40k actors/cluster possible
        # (release/benchmarks/README.md:10). Tasks keep the 1-CPU default.
        resources["CPU"] = 0.0 if num_cpus is None else float(num_cpus)
        from ray_tpu.util import scheduling_strategies as _sched

        info = self.conductor.call(
            "create_actor", spec_bytes,
            options.get("name"), options.get("namespace", "default"),
            resources,
            options.get("max_restarts", 0),
            options.get("max_task_retries", 0),
            options.get("placement_group_id"),
            options.get("get_if_exists", False),
            _sched.to_wire(options.get("scheduling_strategy", "DEFAULT")),
            timeout=None)
        if info["state"] == "DEAD":
            raise exc.ActorDiedError(info["actor_id"],
                                     info.get("death_cause") or "")
        return info

    def submit_actor_task(self, actor_id: str, address: Tuple[str, int],
                          method: str, args: tuple, kwargs: dict,
                          num_returns: int, seqno: int, caller_id: str,
                          max_task_retries: int = 0):
        return_ids = [ObjectID().hex() for _ in range(num_returns)]
        refs = [ObjectRef(oid, locator=tuple(address), owner=self.address)
                for oid in return_ids]
        from . import refcount

        refcount.tracker.wire_incref(refcount.collect_refs(args, kwargs))
        with self._state_lock:
            self._pending_ids.update(return_ids)
        self._register_inflight(
            return_ids, self._submit_pool.submit(
                self._actor_call_bg, actor_id, tuple(address), method, args,
                kwargs, return_ids, seqno, caller_id, max_task_retries,
                _current_traceparent()))
        return refs[0] if num_returns == 1 else refs

    def _await_send_turn(self, caller_id: str, seqno: int) -> None:
        if seqno < 0:
            return
        with self._send_cv:
            self._send_seq.setdefault(caller_id, 0)
            while self._send_seq[caller_id] < seqno and not self._shutdown:
                self._send_cv.wait(0.1)

    def _advance_send_turn(self, caller_id: str, seqno: int) -> None:
        if seqno < 0:
            return
        with self._send_cv:
            if self._send_seq.get(caller_id, 0) <= seqno:
                self._send_seq[caller_id] = seqno + 1
                self._send_cv.notify_all()

    def _actor_call_bg(self, actor_id, address, method, args, kwargs,
                       return_ids, seqno, caller_id, retries,
                       traceparent=None) -> None:
        from . import refcount

        arg_refs = refcount.collect_refs(args, kwargs)
        t0 = time.time()
        ev_name = f"{actor_id[:8]}.{method}"
        try:
            while True:
                pending = client = None
                self._await_send_turn(caller_id, seqno)
                try:
                    client = self.clients.get(address)
                    pending = client.start_call(
                        "actor_task", actor_id, method, args, kwargs,
                        return_ids, seqno, caller_id, _MACHINE_ID,
                        traceparent)
                except ConnectionLost:
                    pass
                finally:
                    self._advance_send_turn(caller_id, seqno)
                if pending is None:
                    # Never delivered (connect/send failed) — always safe to
                    # wait for restart and resend, independent of
                    # max_task_retries (matches the reference's client-side
                    # queueing while an actor is RESTARTING).
                    address = self._wait_actor_restart(actor_id)
                    seqno = -1  # resent call executes unordered
                    continue
                try:
                    reply = client.finish_call(pending, "actor_task",
                                               timeout=None)
                    break
                except (ConnectionLost, RemoteError) as e:
                    unavailable = isinstance(e, ConnectionLost) or isinstance(
                        e.cause, exc.ActorUnavailableError)
                    if not unavailable:
                        raise
                    if retries == 0:
                        raise exc.ActorDiedError(
                            actor_id, "actor died mid-call "
                            "(max_task_retries=0)") from e
                    address = self._wait_actor_restart(actor_id)
                    seqno = -1  # retried call executes unordered
                    if retries > 0:
                        retries -= 1
            self._record_results(return_ids, reply, holder=tuple(address))
            # actor calls show up in the task timeline / actor
            # drill-down like plain tasks (reference task events cover
            # both NORMAL_TASK and ACTOR_TASK)
            self._record_event_raw(return_ids[0], ev_name, t0,
                                   tuple(address), "FINISHED")
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, RemoteError) and isinstance(e.cause,
                                                         exc.RayTpuError):
                err: BaseException = e.cause
            elif isinstance(e, exc.RayTpuError):
                err = e
            else:
                err = exc.TaskError(e, traceback.format_exc(), method)
            for oid in return_ids:
                self.store.put_error(oid, err)
            with self._state_lock:
                self._pending_ids.difference_update(return_ids)
                self._cancelled.difference_update(return_ids)
                for oid in return_ids:
                    self._inflight.pop(oid, None)
            self._notify_object_waiters(return_ids)
            self._record_event_raw(
                return_ids[0], ev_name, t0, tuple(address),
                "CANCELLED" if isinstance(err, exc.TaskCancelledError)
                else "FAILED")
        finally:
            refcount.tracker.wire_decref(arg_refs)

    # --------------------------------------------------------------- pubsub

    def subscribe_channel(self, channel: str, callback) -> None:
        """Route conductor pubsub `channel` messages to `callback`
        (reference: GcsSubscriber; here the conductor pushes on_published
        straight at our RPC server — no long-poll loop)."""
        with self._pub_lock:
            self._pub_handlers.setdefault(channel, []).append(callback)
            need_sub = channel not in self._pub_channels
            if need_sub:
                self._pub_channels.add(channel)
        if need_sub:
            try:
                self.conductor.call("subscribe", channel, self.address,
                                    timeout=10.0)
            except (ConnectionLost, TimeoutError, RemoteError):
                # callers all have polling fallbacks; an unreachable/slow
                # conductor must not turn a subscribe into their failure
                with self._pub_lock:
                    self._pub_channels.discard(channel)

    def unsubscribe_channel(self, channel: str, callback) -> None:
        """Drop a local callback (the conductor-side subscription is
        per-address and shared; it stays)."""
        with self._pub_lock:
            cbs = self._pub_handlers.get(channel)
            if cbs and callback in cbs:
                cbs.remove(callback)

    def _wait_actor_restart(self, actor_id: str,
                            timeout: float = 120.0) -> Tuple[str, int]:
        """Block until the actor is ALIVE again. Event-driven: rides the
        conductor's actor_state pubsub channel (reference GCS actor pubsub,
        gcs_actor_manager.cc state-change publish); the 2s re-query is only
        a safety net for a conductor restart dropping subscriptions."""
        event = threading.Event()

        def on_state(msg) -> None:
            if isinstance(msg, dict) and msg.get("actor_id") == actor_id:
                event.set()

        self.subscribe_channel("actor_state", on_state)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                event.clear()  # before the query: a publish racing it wakes
                info = self.conductor.call("get_actor_info", actor_id,
                                           timeout=10.0)
                if info["state"] == "ALIVE":
                    return tuple(info["address"])
                if info["state"] == "DEAD":
                    raise exc.ActorDiedError(actor_id,
                                             info.get("death_cause") or "")
                event.wait(2.0)
            raise exc.ActorUnavailableError(actor_id, "restart timed out")
        finally:
            self.unsubscribe_channel("actor_state", on_state)

    # --------------------------------------------------------- cancellation

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        """Cancel the task producing `ref` (reference CoreWorker::
        CancelTask, python worker.py:2932 ray.cancel semantics):
        - not yet pushed: the submit thread aborts before/after lease;
        - running: the executor gets a cooperative TaskCancelledError
          injection (force=True kills the worker process instead — the
          guaranteed stop, surfacing through the worker-death path);
        - queued actor call: dropped at dispatch, the actor survives
          (force=True is rejected for actor calls, as in the reference —
          it would kill the whole actor, not one call);
        - a ref owned by another process is forwarded to its owner.
        The caller's get() raises TaskCancelledError immediately either
        way; completion racing the cancel is discarded, not delivered."""
        if force and ref.locator is not None and ref.owner is not None \
                and tuple(ref.locator) != tuple(ref.owner):
            # actor-call refs are minted with locator=executor upfront;
            # task refs start locator-less, put() refs have locator==owner
            raise ValueError(
                "force=True is not supported for actor calls: it would "
                "kill the actor process, failing every other caller "
                "(reference ray.cancel ValueError)")
        owner = tuple(ref.owner) if ref.owner is not None else None
        if owner is not None and owner != self.address:
            # borrowed ref: only the owner knows where it is executing
            # (reference: CancelTask RPC routed to the owning worker)
            try:
                self.clients.get(owner).notify(
                    "cancel_owned_object", ref.id, force,
                    tuple(ref.locator) if ref.locator else None)
            except ConnectionLost:
                pass
            return
        self._cancel_owned(ref.id, force,
                           tuple(ref.locator) if ref.locator else None)

    def _cancel_owned(self, oid: str, force: bool,
                      locator: Optional[Tuple[str, int]]) -> None:
        with self._state_lock:
            still_mine = oid in self._pending_ids
            if still_mine:
                self._cancelled.add(oid)
            where = self._executing_at.get(oid)
        if not still_mine:
            return  # already finished (or not ours): nothing to cancel
        # wake the caller NOW; execution teardown proceeds asynchronously
        self.store.put_error(oid, exc.TaskCancelledError(
            f"task for {oid[:12]}… cancelled"
            + (" (force)" if force else "")))
        self._notify_object_waiters([oid])
        if where is None and locator is not None:
            where = tuple(locator)  # actor call: executor known upfront
        if where is not None:
            try:
                self.clients.get(tuple(where)).notify(
                    "cancel_task", [oid], force)
            except ConnectionLost:
                pass

    # ------------------------------------------------------------ streaming

    def open_stream(self) -> Tuple[str, "queue.Queue"]:
        """Create a local stream endpoint. A remote producer pushes
        (seq, payload) frames at it via the stream_chunk RPC; the consumer
        drains the returned queue. Used by Serve's streaming responses
        (reference: streaming ObjectRefGenerator, replica.py:470)."""
        stream_id = uuid.uuid4().hex
        q: "queue.Queue" = queue.Queue()
        with self._state_lock:
            self._streams[stream_id] = q
        return stream_id, q

    def close_stream(self, stream_id: str) -> None:
        """Drop the endpoint; subsequent producer pushes are acked False
        so the producer can stop generating."""
        with self._state_lock:
            self._streams.pop(stream_id, None)

    # ----------------------------------------------------------- async get

    def get_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def run():
            try:
                fut.set_result(self._get_one(ref, None))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    async def get_async(self, ref: ObjectRef):
        return await asyncio.wrap_future(self.get_future(ref))

    # ------------------------------------------------------------- shutdown

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        from . import refcount

        refcount.tracker.detach()
        # flush the tail of the task-event/span batch so `ray_tpu summary`/
        # `timeline` see short-lived drivers (e.g. submitted jobs)
        try:
            self._flush_task_events()
        except Exception:  # noqa: BLE001 — head may already be gone
            pass
        self._submit_pool.shutdown(wait=False, cancel_futures=True)
        try:
            self._return_all_cached_leases()
        except Exception:  # noqa: BLE001 — conductor may already be gone
            pass
        self.server.stop()
        self.clients.close_all()
        try:
            self.conductor.close()
        except Exception:
            pass
        self.store.shutdown()


class ActorRuntime:
    """Server-side actor state: instance + ordered scheduling queue
    (reference: ActorSchedulingQueue, actor_scheduling_queue.cc — per-caller
    sequence numbers with a reorder buffer; concurrency via a pool when
    max_concurrency > 1, concurrency_group_manager.cc)."""

    def __init__(self, worker: Worker, actor_id: str, cls, args, kwargs,
                 options: Dict[str, Any]):
        self.worker = worker
        self.actor_id = actor_id
        self.options = options
        self.max_concurrency = int(options.get("max_concurrency") or 1)
        if options.get("runtime_env"):
            # dedicated process: applied permanently (reference behavior)
            from . import runtime_env as renv

            ctx = renv.applied(worker.conductor, options["runtime_env"],
                               permanent=True)
            ctx.__enter__()
        self.instance = cls(
            *[worker._materialize(a) for a in args],
            **{k: worker._materialize(v) for k, v in kwargs.items()})
        self._next_seqno: Dict[str, int] = {}
        self._reorder: Dict[str, Dict[int, tuple]] = {}
        self._cancelled: set = set()  # return_ids dropped before dispatch
        self._known: set = set()      # return_ids queued or executing
        # replies go out from a thread that is never an injection target
        # (not in _exec_threads): an async-exc landing mid reply-frame
        # write would corrupt the connection for every later reply
        self._reply_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"actor-reply-{actor_id[:8]}")
        self._cv = threading.Condition()
        self._queue: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._exec_pool = ThreadPoolExecutor(
            max_workers=self.max_concurrency,
            thread_name_prefix=f"actor-{actor_id[:8]}")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        threading.Thread(target=self._dispatch_loop, daemon=True,
                         name="actor-dispatch").start()

    def submit(self, method, args, kwargs, return_ids, seqno, caller_id,
               done_cb, caller_machine=None, traceparent=None) -> None:
        if seqno < 0:
            # unordered (post-restart retry): skip the reorder buffer —
            # ordering across a restart boundary is best-effort, matching the
            # reference's at-least-once actor-retry semantics.
            with self._cv:
                self._known.update(return_ids)
            self._queue.put((method, args, kwargs, return_ids, done_cb,
                             caller_machine, traceparent))
            return
        with self._cv:
            self._known.update(return_ids)
            # A fresh runtime (post-restart) may first see a caller mid-stream;
            # adopt its current seqno as the starting point.
            expected = self._next_seqno.setdefault(caller_id, seqno)
            buf = self._reorder.setdefault(caller_id, {})
            buf[seqno] = (method, args, kwargs, return_ids, done_cb,
                          caller_machine, traceparent)
            while expected in buf:
                self._queue.put(buf.pop(expected))
                expected += 1
            self._next_seqno[caller_id] = expected

    def _dispatch_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            if self.max_concurrency == 1:
                self._run_one_safe(item)
            else:
                self._exec_pool.submit(self._run_one_safe, item)
            # don't pin the last call's args while idle in queue.get()
            item = None

    def _run_one_safe(self, item) -> None:
        try:
            self._run_one(item)
        except exc.TaskCancelledError:
            # stray async-exc that fired after _run_one delivered its
            # reply: absorb it so the dispatch/pool thread survives
            pass

    def cancel(self, object_ids) -> bool:
        """Mark queued calls cancelled (dropped with TaskCancelledError at
        dispatch — the actor itself survives; reference: pending actor
        tasks cancel with TaskCancelledError, running ones are interrupted
        via the worker's async-exc path). Only ids still queued/executing
        here are marked — a cancel racing an already-delivered completion
        must not leave a permanent mark."""
        with self._cv:
            live = [oid for oid in object_ids if oid in self._known]
            self._cancelled.update(live)
        return bool(live)

    def _run_one(self, item) -> None:
        (method, args, kwargs, return_ids, done_cb, caller_machine,
         traceparent) = item
        with self._cv:
            dropped = any(oid in self._cancelled for oid in return_ids)
            self._cancelled.difference_update(return_ids)
            if dropped:
                self._known.difference_update(return_ids)
        if dropped:
            err0 = exc.TaskCancelledError(f"{method} cancelled while queued")
            done_cb([(oid, "error", err0) for oid in return_ids])
            return
        delivered = [False]

        def deliver(reply) -> None:
            # exactly-once and hang-proof: the reply is handed to the
            # reply pool (whose thread is never an injection target) and
            # the flag flips only after the handoff succeeded. A stray
            # TaskCancelledError inside submit() retries; the worst case
            # is a duplicate enqueue, and the RPC client drops replies
            # with an already-settled req_id.
            while not delivered[0]:
                try:
                    self._reply_pool.submit(done_cb, reply)
                    delivered[0] = True
                except exc.TaskCancelledError:
                    continue

        ident = threading.get_ident()
        with self.worker._state_lock:
            for oid in return_ids:
                self.worker._exec_threads[oid] = ident
        try:
            self._call_and_reply(method, args, kwargs, return_ids, deliver,
                                 caller_machine, traceparent)
        except exc.TaskCancelledError as e:
            # async-exc landed in the teardown window after the method
            # returned — deliver the cancel (no-op if already delivered)
            deliver([(oid, "error", e) for oid in return_ids])
        finally:
            # marks for calls cancelled while RUNNING (not queued) are
            # consumed alongside the pops, not leaked
            def consume_marks() -> None:
                with self._cv:
                    self._cancelled.difference_update(return_ids)
                    self._known.difference_update(return_ids)

            self.worker._pop_exec_threads(return_ids, also=consume_marks)

    def _call_and_reply(self, method, args, kwargs, return_ids, deliver,
                        caller_machine, traceparent) -> None:
        try:
            if method == "__ray_tpu_col_init__":
                # universal hook so create_collective_group works on any
                # actor class (reference declarative mode, collective.py:151)
                from ray_tpu.util import collective as _collective

                fn = _collective.init_collective_group
            elif method == "__ray_tpu_compiled_loop__":
                # universal hook pinning a compiled-DAG loop on this actor
                # (reference compiled_dag_node.py do_exec_compiled_task :43)
                import functools as _functools

                from ray_tpu.dag.compiled_dag import run_actor_loop

                fn = _functools.partial(run_actor_loop, self.instance)
            else:
                fn = getattr(self.instance, method)
            args = tuple(self.worker._materialize(a) for a in args)
            kwargs = {k: self.worker._materialize(v)
                      for k, v in kwargs.items()}
            if traceparent:
                from ray_tpu.util import tracing

                span_name = (f"actor:{type(self.instance).__name__}"
                             f".{method}")
                with tracing.span(span_name, traceparent=traceparent):
                    result = fn(*args, **kwargs)
            else:
                result = fn(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = self._run_coroutine(result)
            results = [result] if len(return_ids) == 1 else list(result)
            reply = [self.worker._store_result(oid, value, caller_machine)
                     for oid, value in zip(return_ids, results)]
        except SystemExit:
            err = exc.ActorDiedError(self.actor_id, "exit_actor() called")
            deliver([(oid, "error", err) for oid in return_ids])
            self._graceful_exit()
            return
        except exc.TaskCancelledError as e:
            reply = [(oid, "error", e) for oid in return_ids]
        except BaseException as e:  # noqa: BLE001
            err2 = exc.TaskError(e, traceback.format_exc(), method)
            reply = [(oid, "error", err2) for oid in return_ids]
        deliver(reply)

    def ensure_loop(self) -> asyncio.AbstractEventLoop:
        """The actor's persistent event loop — ALL of this actor's async
        work must share it so loop-bound primitives (asyncio.Queue/Lock
        created in async methods) stay usable across calls."""
        if self._loop is None:
            self._loop = asyncio.new_event_loop()
            threading.Thread(target=self._loop.run_forever, daemon=True,
                             name="actor-asyncio").start()
        return self._loop

    def _run_coroutine(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.ensure_loop()
                                                ).result()

    def _graceful_exit(self) -> None:
        # flush the in-flight reply (exit_actor's own ActorDiedError) —
        # os._exit would otherwise drop it before the frame hits the wire
        self._reply_pool.shutdown(wait=True)
        try:
            self.worker.conductor.call("report_actor_exit", self.actor_id,
                                       "exit_actor() called", timeout=5.0)
        except Exception:
            pass
        # Deliberately NOT unlinking the shm arena here: a consumer may
        # hold a fetched-but-not-yet-mapped reference to a block in it
        # (put_shm_reference records the segment NAME lazily); unlinking
        # would turn its first get() into ObjectLostError. The leaked
        # segment is bounded per exited actor and swept at cluster stop
        # (object_store.cleanup_leaked_segments).
        os._exit(0)


class WorkerHandler:
    """RPC surface of a worker process (reference core_worker.proto:
    PushTask, GetObjectStatus, object-location queries)."""

    def __init__(self, worker: Worker):
        self.w = worker

    def ping(self) -> str:
        return "pong"

    def store_stats(self) -> dict:
        """Object-store introspection for the state API (reference
        `ray memory` / StateHead object aggregation)."""
        s = self.w.store.stats()
        s["worker_id"] = self.w.worker_id
        s["actor_id"] = getattr(self.w._actor_runtime, "actor_id", None) \
            if self.w._actor_runtime else None
        return s

    def push_task(self, wire: dict) -> list:
        return self.w.execute_task(wire)

    def become_actor(self, actor_id: str, spec_bytes: bytes) -> bool:
        cls, args, kwargs, options = serialization.loads(spec_bytes)
        self.w._actor_runtime = ActorRuntime(self.w, actor_id, cls, args,
                                             kwargs, options)
        return True

    # actor_task is enqueued from the RPC reader thread in frame-arrival
    # order (see RpcServer._conn_loop) so the per-caller reorder buffer sees
    # seqnos arrive monotonically; the reply goes out when execution ends.
    _async_reply_methods = frozenset({"actor_task"})

    def actor_task(self, reply_cb, actor_id: str, method: str, args, kwargs,
                   return_ids, seqno: int, caller_id: str,
                   caller_machine: Optional[str] = None,
                   traceparent: Optional[str] = None) -> None:
        rt = self.w._actor_runtime
        if rt is None or rt.actor_id != actor_id:
            e = exc.ActorUnavailableError(actor_id,
                                          "no such actor on this worker")
            reply_cb(False, (e, ""))
            return
        rt.submit(method, args, kwargs, return_ids, seqno, caller_id,
                  lambda reply: reply_cb(True, reply), caller_machine,
                  traceparent)

    def fetch_object(self, object_id: str, machine_id: Optional[str] = None):
        """Serve a fetch. Same-host peers (or legacy callers passing no
        machine id) get the shm zero-copy reference; cross-host peers get
        the payload inline, or a 'stream' header directing them to pull
        fetch_object_range chunks (reference object_manager chunked
        push/pull, pull_manager.cc)."""
        same_host = machine_id is None or machine_id == _MACHINE_ID
        try:
            if same_host:
                meta, shm_name, layout, inline = self.w.store.export(object_id)
                if shm_name is not None:
                    return ("shm", (meta, shm_name, layout))
                return ("inline", (meta, inline))
            meta, total, sizes = self.w.store.stream_info(object_id)
            if total > _fetch_chunk():
                return ("stream", (meta, total, sizes))
            data = self.w.store.read_range(object_id, 0, total)
            bufs, off = [], 0
            for s in sizes:
                bufs.append(data[off:off + s])
                off += s
            return ("inline", (meta, bufs))
        except exc.RayTpuError as e:
            return ("error", e)

    def fetch_object_range(self, object_id: str, start: int,
                           size: int) -> bytes:
        return self.w.store.read_range(object_id, start,
                                       min(size, _fetch_chunk()))

    def resolve_object(self, object_id: str,
                       machine_id: Optional[str] = None):
        """Owner-side: block until ready, then return the value or its
        location (reference: ownership-based object directory)."""
        w = self.w
        while True:
            if w.store.contains(object_id):
                return self.fetch_object(object_id, machine_id)
            loc = w._locator_of(object_id)
            if loc is not None:
                return ("locator", loc)
            if not w._is_pending_local(object_id):
                return ("error", exc.ObjectLostError(object_id,
                                                     "unknown to owner"))
            w.store.wait_ready(object_id, 0.2)

    def resolve_object_location(self, object_id: str,
                                max_wait: Optional[float] = None) -> bool:
        """True once the object is reachable; False if `max_wait` elapses
        while it is still legitimately pending (caller re-polls — keeps
        the requester's RPC bounded instead of parking it here)."""
        w = self.w
        deadline = None if max_wait is None else time.monotonic() + max_wait
        while True:
            if w.store.contains(object_id) or w._locator_of(object_id):
                return True
            if not w._is_pending_local(object_id):
                raise exc.ObjectLostError(object_id, "unknown to owner")
            if deadline is not None and time.monotonic() >= deadline:
                return False
            w.store.wait_ready(object_id, 0.2)

    def subscribe_object(self, object_id: str,
                         waiter: Tuple[str, int]) -> bool:
        """Register `waiter` for an object_available push when `object_id`
        resolves (value OR error); True if it is already ready, in which
        case no push will follow. Replaces object_ready polling for wait()
        (reference: WaitForObjectEviction-style owner callbacks)."""
        w = self.w
        if w.store.contains(object_id) or w._locator_of(object_id):
            return True
        with w._state_lock:
            w._object_waiters.setdefault(object_id, set()).add(tuple(waiter))
        # re-check AFTER registering: a result recorded between the first
        # check and the insert has already popped (or will never see) the
        # table entry — without this the waiter could miss its only push
        if w.store.contains(object_id) or w._locator_of(object_id):
            with w._state_lock:
                s = w._object_waiters.get(object_id)
                if s is not None:
                    s.discard(tuple(waiter))
                    if not s:
                        w._object_waiters.pop(object_id, None)
            return True
        return False

    def object_available(self, object_ids: List[str]) -> None:
        """Owner's readiness push for ids we subscribed to."""
        w = self.w
        with w._state_lock:
            w._remote_ready.update(object_ids)
            if len(w._remote_ready) > 1 << 16:
                # bounded: dropping entries only costs a re-subscribe RPC
                w._remote_ready.clear()
                w._subscribed.clear()
                w._remote_ready.update(object_ids)
        w.store.notify_waiters()

    def release_object(self, object_id: str) -> None:
        self.w.store.delete(object_id)

    def free_objects(self, object_ids: List[str]) -> None:
        for oid in object_ids:
            self.w.store.delete(oid)

    def stream_chunk(self, stream_id: str, seq: int, payload: bytes) -> bool:
        """Producer push into a local stream endpoint; False tells the
        producer the consumer is gone (stop generating)."""
        with self.w._state_lock:
            q = self.w._streams.get(stream_id)
        if q is None:
            return False
        q.put((seq, payload))
        return True

    def start_device_profile(self, tag: str) -> str:
        """Begin a jax.profiler trace in THIS worker process (driver-side
        API: ray_tpu.util.profiling.profile_actor)."""
        from ray_tpu.util import profiling

        return profiling.start_profile(tag)

    def stop_device_profile(self) -> str:
        from ray_tpu.util import profiling

        return profiling.stop_profile()

    def refcount_update(self, from_addr, entries) -> None:
        """Batched borrower incref/adopt/drop messages (reference
        reference_count.h borrower protocol)."""
        from . import refcount

        refcount.tracker.apply_remote(from_addr, entries)

    def cancel_task(self, object_ids: List[str], force: bool = False) -> bool:
        """Cancel execution of the task producing `object_ids` (reference
        CoreWorker::CancelTask / HandleCancelTask core_worker.cc).

        force=True kills this worker process — the guaranteed stop, routed
        through the normal worker-death path on the submitter/conductor.
        Otherwise a TaskCancelledError is raised asynchronously in the
        executing thread (cooperative: a thread blocked in native code,
        e.g. time.sleep, sees it only when it re-enters the interpreter —
        same best-effort contract as the reference's non-force cancel).
        Also drops matching queued actor calls."""
        if force:
            # only if a target is STILL executing here: the task may have
            # finished (and this worker been leased to someone else's task)
            # between the owner reading _executing_at and this arriving —
            # killing then would take down an innocent task
            with self.w._state_lock:
                live = any(oid in self.w._exec_threads for oid in object_ids)
            if not live:
                return False
            threading.Thread(target=lambda: (time.sleep(0.05), os._exit(1)),
                             daemon=True).start()
            return True
        hit = False
        rt = self.w._actor_runtime
        if rt is not None:
            hit = rt.cancel(object_ids) or hit
        import ctypes

        # Inject while HOLDING _state_lock: the executor pops its
        # _exec_threads entry under the same lock in its teardown, so a
        # finished task can never be "hit" after its pop — the injection
        # lands in the target task's frame or its guarded teardown, never
        # in the next task reusing the pool thread.
        with self.w._state_lock:
            idents = {self.w._exec_threads.get(oid) for oid in object_ids}
            idents.discard(None)
            for ident in idents:
                n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(ident),
                    ctypes.py_object(exc.TaskCancelledError))
                if n > 1:  # hit more than one thread state: revoke
                    ctypes.pythonapi.PyThreadState_SetAsyncExc(
                        ctypes.c_ulong(ident), None)
                hit = hit or n == 1
        return hit

    def cancel_owned_object(self, object_id: str, force: bool,
                            locator) -> None:
        """A borrower's forwarded cancel for an object WE own (reference:
        CancelTask RPC arriving at the owning core worker)."""
        self.w._cancel_owned(object_id, bool(force),
                             tuple(locator) if locator else None)

    def on_published(self, channel: str, message: Any) -> None:
        """Conductor pubsub delivery: fan out to local subscribers
        registered via Worker.subscribe_channel."""
        w = self.w
        with w._pub_lock:
            cbs = list(w._pub_handlers.get(channel, ()))
        for cb in cbs:
            try:
                cb(message)
            except Exception:  # noqa: BLE001 — one bad callback ≠ all
                pass

    def shutdown_worker(self) -> None:
        threading.Thread(target=lambda: (time.sleep(0.05), os._exit(0)),
                         daemon=True).start()
