"""Worker process spawning, shared by the conductor's head-local pool and
per-host node agents (reference: raylet WorkerPool starting
default_worker.py, src/ray/raylet/worker_pool.h:343)."""
from __future__ import annotations

import os
import subprocess
import sys
from typing import Dict, Optional, Tuple


def spawn_worker_process(worker_id: str,
                         conductor_address: Tuple[str, int],
                         session_dir: str,
                         worker_env: Optional[Dict[str, str]] = None,
                         env_extra: Optional[Dict[str, str]] = None,
                         node_id: Optional[str] = None) -> subprocess.Popen:
    """Start one ray_tpu worker subprocess wired to the conductor."""
    host, port = conductor_address
    env = dict(os.environ)
    env.update(worker_env or {})
    if env_extra:
        env.update(env_extra)
    env["RAY_TPU_WORKER_ID"] = worker_id
    env["RAY_TPU_CONDUCTOR"] = f"{host}:{port}"
    env["RAY_TPU_SESSION_DIR"] = session_dir
    if node_id:
        env["RAY_TPU_NODE_ID"] = node_id
    logs = os.path.join(session_dir, "logs")
    os.makedirs(logs, exist_ok=True)
    out = open(os.path.join(logs, f"worker-{worker_id[:12]}.log"), "ab")
    # -S skips `site` (whose sitecustomize registers the TPU PJRT plugin
    # and imports all of jax — ~2s of cold-start the worker doesn't need;
    # workers are host-side, the driver owns the chips). Site packages are
    # re-exposed via PYTHONPATH. Set RAY_TPU_WORKER_FULL_SITE=1 in
    # worker_env for workers that must see the TPU runtime.
    cmd = [sys.executable, "-m", "ray_tpu._private.worker_main"]
    if env.get("RAY_TPU_WORKER_FULL_SITE") != "1":
        import site

        paths = list(site.getsitepackages())
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        paths.append(repo_root)
        if env.get("PYTHONPATH"):
            paths.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(paths)
        cmd.insert(1, "-S")
    return subprocess.Popen(
        cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
        start_new_session=True)
