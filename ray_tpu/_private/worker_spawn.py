"""Worker process spawning, shared by the conductor's head-local pool and
per-host node agents (reference: raylet WorkerPool starting
default_worker.py, src/ray/raylet/worker_pool.h:343).

Two paths:
- fork server (default): a pre-warmed template process forks
  workers in ~10ms (see fork_server.py) — the analog of the reference
  pool's prestarted workers, sized for actor churn.
- direct subprocess: cold interpreter start (~200ms); the fallback when
  the fork server is unavailable (non-linux, full-site workers that must
  load the TPU plugin, or the template died).
"""
from __future__ import annotations

import os
import pickle
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, Optional, Tuple


class ForkedProc:
    """Popen-shaped handle for a fork-server worker. The worker is the
    TEMPLATE's child (the template reaps it), so liveness is probed with
    signal 0 instead of waitpid; the exit code is unknowable here and
    reported as 0."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self.returncode = 0
            return 0
        except PermissionError:
            return None
        return None

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.poll() is None:
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.01)
        return self.returncode or 0

    def send_signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            self.returncode = self.returncode if self.returncode is not None \
                else 0

    def terminate(self) -> None:
        self.send_signal(signal.SIGTERM)

    def kill(self) -> None:
        self.send_signal(signal.SIGKILL)


class _ForkServer:
    """Client + lifecycle for one template process, keyed by session."""

    def __init__(self, sock_path: str, proc: subprocess.Popen):
        self.sock_path = sock_path
        self.proc = proc
        self.lock = threading.Lock()

    def spawn(self, env: Dict[str, str], log_path: str) -> ForkedProc:
        req = pickle.dumps({"env": env, "log_path": log_path})
        with self.lock:  # template serves sequentially
            conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                conn.settimeout(10.0)
                conn.connect(self.sock_path)
                conn.sendall(struct.pack("<I", len(req)) + req)
                buf = b""
                while len(buf) < 4:
                    chunk = conn.recv(4 - len(buf))
                    if not chunk:
                        raise EOFError("fork server closed mid-reply")
                    buf += chunk
            finally:
                conn.close()
        (pid,) = struct.unpack("<i", buf)
        return ForkedProc(pid)

    def stop(self) -> None:
        try:
            self.proc.terminate()
            self.proc.wait(timeout=3.0)
        except Exception:  # noqa: BLE001 — best-effort teardown
            try:
                self.proc.kill()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass


_fork_servers: Dict[str, _ForkServer] = {}
_fork_servers_lock = threading.Lock()


def _apply_no_site_paths(env: Dict[str, str]) -> None:
    """-S/PYTHONPATH wiring shared by both spawn paths: skip `site`
    (whose sitecustomize registers the TPU PJRT plugin and imports all of
    jax — ~2s of cold-start the worker doesn't need; workers are
    host-side, the driver owns the chips), re-exposing site packages via
    PYTHONPATH. Set RAY_TPU_WORKER_FULL_SITE=1 in worker_env for workers
    that must see the TPU runtime."""
    import site

    paths = list(site.getsitepackages())
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    paths.append(repo_root)
    if env.get("PYTHONPATH"):
        paths.append(env["PYTHONPATH"])
    env["PYTHONPATH"] = os.pathsep.join(paths)


def _get_fork_server(session_dir: str,
                     base_env: Dict[str, str]) -> Optional[_ForkServer]:
    if sys.platform != "linux" or os.environ.get("RAY_TPU_NO_FORK_SERVER"):
        return None
    with _fork_servers_lock:
        fs = _fork_servers.get(session_dir)
        if fs is not None and fs.proc.poll() is None:
            return fs
        if fs is not None:
            _fork_servers.pop(session_dir, None)
        sock_path = os.path.join(session_dir, "fork_server.sock")
        tmpl_env = dict(base_env)
        _apply_no_site_paths(tmpl_env)
        proc = None
        try:
            proc = subprocess.Popen(
                [sys.executable, "-S", "-m",
                 "ray_tpu._private.fork_server", sock_path],
                env=tmpl_env, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, start_new_session=True)
            # bounded readiness wait: a wedged template import must not
            # hold _fork_servers_lock forever (that would freeze every
            # future spawn cluster-wide); on timeout, kill + cold-spawn
            import select

            ready, _, _ = select.select([proc.stdout], [], [], 60.0)
            line = proc.stdout.readline() if ready else b""
            if b"READY" not in line:
                raise RuntimeError(f"fork server not ready: {line!r}")
        except Exception:  # noqa: BLE001 — caller falls back to subprocess
            if proc is not None:
                try:
                    proc.kill()
                    proc.wait(timeout=3.0)
                except Exception:  # noqa: BLE001 — already gone
                    pass
            return None
        fs = _ForkServer(sock_path, proc)
        _fork_servers[session_dir] = fs
        return fs


def stop_fork_server(session_dir: str) -> None:
    with _fork_servers_lock:
        fs = _fork_servers.pop(session_dir, None)
    if fs is not None:
        fs.stop()


def spawn_worker_process(worker_id: str,
                         conductor_address: Tuple[str, int],
                         session_dir: str,
                         worker_env: Optional[Dict[str, str]] = None,
                         env_extra: Optional[Dict[str, str]] = None,
                         node_id: Optional[str] = None):
    """Start one ray_tpu worker wired to the conductor; returns a
    Popen-shaped handle (subprocess.Popen or ForkedProc)."""
    host, port = conductor_address
    env = dict(os.environ)
    env.update(worker_env or {})
    if env_extra:
        env.update(env_extra)
    env["RAY_TPU_WORKER_ID"] = worker_id
    env["RAY_TPU_CONDUCTOR"] = f"{host}:{port}"
    env["RAY_TPU_SESSION_DIR"] = session_dir
    if node_id:
        env["RAY_TPU_NODE_ID"] = node_id
    logs = os.path.join(session_dir, "logs")
    os.makedirs(logs, exist_ok=True)
    log_path = os.path.join(logs, f"worker-{worker_id[:12]}.log")

    if env.get("RAY_TPU_WORKER_FULL_SITE") != "1":
        fs = _get_fork_server(
            session_dir, dict(os.environ, **(worker_env or {})))
        if fs is not None:
            child_env = dict(env)
            _apply_no_site_paths(child_env)
            try:
                return fs.spawn(child_env, log_path)
            except Exception:  # noqa: BLE001 — template died: cold spawn
                stop_fork_server(session_dir)
        _apply_no_site_paths(env)
        cmd = [sys.executable, "-S", "-m", "ray_tpu._private.worker_main"]
    else:
        cmd = [sys.executable, "-m", "ray_tpu._private.worker_main"]

    out = open(log_path, "ab")
    return subprocess.Popen(
        cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
        start_new_session=True)
