"""Rotary position embeddings (RoPE) — split-half (GPT-NeoX) convention.

TPU notes: cos/sin tables are precomputed fp32 and broadcast (tiny HBM
cost); the rotation is pure elementwise work that XLA fuses into the
surrounding QK projections, so no Pallas kernel is warranted here."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rope_table(head_dim: int, max_seq_len: int,
               theta: float = 10000.0) -> Tuple[jax.Array, jax.Array]:
    """(cos, sin), each [max_seq_len, head_dim // 2], fp32."""
    if head_dim % 2:
        raise ValueError("RoPE needs an even head_dim")
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [T, hd/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Rotate q or k. x: [B, T, H, hd]; cos/sin: [>=T, hd/2];
    positions: optional [B, T] int32 (defaults to arange — use for
    decode-time offsets)."""
    t = x.shape[1]
    if positions is None:
        c = cos[:t][None, :, None, :]  # [1, T, 1, hd/2]
        s = sin[:t][None, :, None, :]
    else:
        c = cos[positions][:, :, None, :]  # [B, T, 1, hd/2]
        s = sin[positions][:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)
