"""ray_tpu.ops: TPU compute kernels (Pallas) with XLA fallbacks.

The reference has no custom kernels — its compute path is whatever torch
ships (SURVEY.md §2.3: Ray's role is gang-scheduling; math is delegated).
Here the hot ops are first-class: flash attention on the MXU via Pallas,
ring attention for sequence parallelism over the ICI `sp` axis, and fused
layernorm. Every op has a pure-XLA fallback so the same code runs on the
CPU test mesh (`interpret`/fallback) and real TPU chips (Mosaic).
"""
from .attention import flash_attention, mha_reference  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from .moe import load_balancing_loss, moe_ffn  # noqa: F401
from .layers import layer_norm, rms_norm  # noqa: F401
