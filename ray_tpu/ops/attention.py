"""Flash attention for TPU: Pallas kernel (MXU-tiled, online softmax).

New capability relative to the reference (which has no kernels of its own —
SURVEY.md §5.7); the design follows the standard blockwise-softmax flash
attention recipe mapped onto TPU constraints from the Pallas guide:
128-aligned q/kv blocks feeding the 128x128 MXU, fp32 accumulators, causal
masking via broadcasted_iota, and a `@pl.when` skip of fully-masked KV
blocks so causal attention does ~half the FLOPs.

`flash_attention` dispatches: Pallas kernel on TPU backends (or
`interpret=True` when forced), jnp reference otherwise. The backward pass
is a checkpointed recompute (custom_vjp over the reference math), the right
memory/FLOPs trade on HBM-bound TPUs.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  sm_scale: Optional[float] = None) -> jax.Array:
    """Plain XLA multi-head attention. q,k,v: [B, T, H, D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool), k=tk - tq)
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, sm_scale: float,
                  causal: bool, block_q: int, block_k: int, kv_len: int,
                  q_offset: int):
    """One (batch*head, q_block) program; loops KV blocks with online
    softmax. Refs: q [block_q, D], k/v [kv_len, D], o [block_q, D].
    q_offset = kv_len - q_len aligns queries to the END of the kv sequence
    (decode-style), matching mha_reference's tril(k=tk-tq)."""
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32) * sm_scale
    d = q.shape[-1]

    m0 = jnp.full((block_q, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)

    num_kv_blocks = pl.cdiv(kv_len, block_k)
    if causal:
        # KV blocks strictly after this q block's diagonal are fully masked.
        num_kv_blocks = jnp.minimum(
            num_kv_blocks,
            (q_offset + qi * block_q + block_q + block_k - 1) // block_k)

    def body(ki, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        if causal:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m, l, acc = jax.lax.fori_loop(0, num_kv_blocks, body, (m0, l0, acc0))
    # Fully-masked rows (l == 0) only occur with kv_len < block alignment.
    o_ref[...] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, causal: bool, sm_scale: float,
                      block_q: int, block_k: int,
                      interpret: bool) -> jax.Array:
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    # flatten batch*heads into the grid's first axis; time-major per head
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)

    grid = (b * h, pl.cdiv(tq, block_q))
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=tk, q_offset=tk - tq)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, tk, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda g, i: (g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * tq * tk * d,
            bytes_accessed=(qf.size + kf.size + vf.size) * qf.dtype.itemsize,
            transcendentals=b * h * tq * tk),
    )(qf, kf, vf)
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)


def _use_pallas() -> bool:
    if pltpu is None:
        return False
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Fused attention. q,k,v: [batch, time, heads, head_dim] (kv time may
    differ). Pallas on TPU; XLA reference elsewhere. Gradients recompute
    attention blockwise (no O(T^2) residuals)."""
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)[0]


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    if _use_pallas() and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0 \
            and (q.shape[-1] % 128 == 0 or q.shape[-1] == 64):
        out = _flash_fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                                interpret=False)
    else:
        out = mha_reference(q, k, v, causal, scale)
    return out, (q, k, v)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v = res
    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale

    def ref(q_, k_, v_):
        return mha_reference(q_, k_, v_, causal, scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
