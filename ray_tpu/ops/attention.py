"""Flash attention for TPU: Pallas kernels (MXU-tiled, online softmax),
forward AND backward.

New capability relative to the reference (which has no kernels of its own —
SURVEY.md §5.7); the design follows the standard blockwise-softmax flash
attention recipe mapped onto TPU constraints from the Pallas guide:
128-aligned q/kv blocks feeding the 128x128 MXU, fp32 accumulators, causal
masking via broadcasted_iota, and fully-masked-block skipping so causal
attention does ~half the FLOPs.

The backward pass is two Pallas kernels (the FlashAttention-2 recipe):
- dq kernel: grid over q blocks, inner loop over kv blocks;
- dkv kernel: grid over kv blocks, inner loop over q blocks;
both recompute P = exp(S - L) from the forward's saved logsumexp L (stored
lane-broadcast as [B*H, T, 128] f32, the same layout jax's own TPU kernel
uses) and the precomputed row term D = rowsum(dO * O).

`flash_attention` dispatches: Pallas kernel on TPU backends (or
`interpret=True` when RAY_TPU_PALLAS_INTERPRET=1, which is how CPU CI
tests the hardware code path), jnp reference otherwise.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable jaxlib builds
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# block shapes tuned on v5e; env overrides for bench sweeps
DEFAULT_BLOCK_Q = int(os.environ.get("RAY_TPU_FLASH_BLOCK_Q", "1024"))
DEFAULT_BLOCK_K = int(os.environ.get("RAY_TPU_FLASH_BLOCK_K", "1024"))
_LANES = 8  # LSE/D are broadcast over a small minor dim (sublane tile);
#             keeping it at 8 rather than the 128-lane width cuts the HBM
#             traffic of the side outputs 16x
_NEG_INF = -1e30
_LOG2E = 1.4426950408889634  # kernels work in log2 domain: exp2 is the
_LN2 = 0.6931471805599453    # cheap VPU transcendental; scale*log2(e) is
#                              folded into q so softmax needs only exp2.


def _parallel_grid_params(n_axes: int, interpret: bool):
    """Mosaic dimension_semantics: every grid axis of these kernels is
    embarrassingly parallel (no cross-program carries), which lets the
    compiler software-pipeline block DMA against compute instead of
    assuming a sequential grid. No-op in interpret mode / without pltpu."""
    if interpret or pltpu is None:
        return None
    try:
        return pltpu.CompilerParams(
            dimension_semantics=("parallel",) * n_axes)
    except Exception:  # noqa: BLE001 — older pallas: params shape moved
        return None


def mha_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  sm_scale: Optional[float] = None) -> jax.Array:
    """Plain XLA multi-head attention. q,k,v: [B, T, H, D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((tq, tk), dtype=bool), k=tk - tq)
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------- forward


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      sm_scale: float, causal: bool, block_q: int,
                      block_k: int, kv_len: int, q_offset: int):
    """One (batch*head, q_block) program; loops KV blocks with online
    softmax. Refs: q [block_q, D], k/v [kv_len, D], o [block_q, D],
    lse [block_q, LANES] (logsumexp broadcast over lanes).
    q_offset = kv_len - q_len aligns queries to the END of the kv sequence
    (decode-style), matching mha_reference's tril(k=tk-tq)."""
    qi = pl.program_id(1)
    # log2-domain: fold sm_scale*log2(e) into q; softmax uses exp2 only.
    # Matmul operands stay in the input dtype (bf16 on the fast path —
    # f32 MXU passes are ~6x slower); accumulation is always f32.
    cd = q_ref.dtype
    q = (q_ref[...].astype(jnp.float32) * (sm_scale * _LOG2E)).astype(cd)
    d = q.shape[-1]

    m0 = jnp.full((block_q, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((block_q, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((block_q, d), dtype=jnp.float32)

    num_kv_blocks = pl.cdiv(kv_len, block_k)
    num_full_blocks = num_kv_blocks
    if causal:
        # KV blocks strictly after this q block's diagonal are fully masked.
        num_kv_blocks = jnp.minimum(
            num_kv_blocks,
            (q_offset + qi * block_q + block_q + block_k - 1) // block_k)
        # Blocks entirely below the diagonal need no mask compute at all;
        # two loops (full, then diagonal-straddling) keep the hot loop free
        # of iota/select VPU work.
        num_full_blocks = jnp.maximum(
            0, (q_offset + qi * block_q + 1 - block_k) // block_k + 1)

    def body(ki, carry, apply_mask):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [block_q, block_k]
        if apply_mask:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(cd), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    carry = jax.lax.fori_loop(
        0, num_full_blocks, functools.partial(body, apply_mask=False),
        (m0, l0, acc0))
    m, l, acc = jax.lax.fori_loop(
        num_full_blocks, num_kv_blocks,
        functools.partial(body, apply_mask=True), carry)
    # Fully-masked rows (l == 0) only occur with kv_len < block alignment.
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    # natural-log LSE for the API: ln(sum exp(s_nat - 0)) recovered from
    # the log2-domain running (m, l).
    lse = (m + jnp.log2(l_safe)) * _LN2  # [block_q, 1]
    lse_ref[...] = jnp.broadcast_to(lse, (block_q, _LANES))


def _flash_fwd_pallas(q, k, v, causal: bool, sm_scale: float,
                      block_q: int, block_k: int, interpret: bool):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    # flatten batch*heads into the grid's first axis; time-major per head
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)

    grid = (b * h, pl.cdiv(tq, block_q))
    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=tk, q_offset=tk - tq)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, tk, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda g, i: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda g, i: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq, _LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_parallel_grid_params(2, interpret),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * tq * tk * d,
            bytes_accessed=(qf.size + kf.size + vf.size) * qf.dtype.itemsize,
            transcendentals=b * h * tq * tk),
    )(qf, kf, vf)
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3), lse


# -------------------------------------------------------------- backward


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dcor_ref,
                         dq_ref, *, sm_scale: float, causal: bool,
                         block_q: int, block_k: int, kv_len: int,
                         q_offset: int):
    """dQ for one q block: loop over kv blocks.
    Refs: q/do/dq [block_q, D], k/v [kv_len, D], lse/dcor [block_q, LANES]
    (dcor = rowsum(dO * O), the softmax correction term)."""
    qi = pl.program_id(1)
    cd = q_ref.dtype
    q = (q_ref[...].astype(jnp.float32) * (sm_scale * _LOG2E)).astype(cd)
    do = do_ref[...]
    lse2 = lse_ref[:, :1] * _LOG2E   # [block_q, 1], log2 domain
    dcor = dcor_ref[:, :1]
    d = q.shape[-1]

    num_kv_blocks = pl.cdiv(kv_len, block_k)
    num_full_blocks = num_kv_blocks
    if causal:
        num_kv_blocks = jnp.minimum(
            num_kv_blocks,
            (q_offset + qi * block_q + block_q + block_k - 1) // block_k)
        num_full_blocks = jnp.maximum(
            0, (q_offset + qi * block_q + 1 - block_k) // block_k + 1)

    def body(ki, dq_acc, apply_mask):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :]
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if apply_mask:
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp2(s - lse2)                    # [block_q, block_k]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # [block_q, block_k]
        ds = (p * (dp - dcor)).astype(cd)
        return dq_acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_full_blocks, functools.partial(body, apply_mask=False),
        jnp.zeros((block_q, d), jnp.float32))
    dq = jax.lax.fori_loop(
        num_full_blocks, num_kv_blocks,
        functools.partial(body, apply_mask=True), dq)
    dq_ref[...] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dcor_ref,
                          dk_ref, dv_ref, *, sm_scale: float, causal: bool,
                          block_q: int, block_k: int, q_len: int,
                          q_offset: int):
    """dK/dV for one kv block: loop over q blocks.
    Refs: k/v/dk/dv [block_k, D], q/do [q_len, D], lse/dcor [q_len, LANES].
    """
    ki = pl.program_id(1)
    cd = k_ref.dtype
    k_scaled = (k_ref[...].astype(jnp.float32)
                * (sm_scale * _LOG2E)).astype(cd)
    v_blk = v_ref[...]
    d = k_scaled.shape[-1]

    num_q_blocks = pl.cdiv(q_len, block_q)
    start_q = 0
    first_full_q = 0
    if causal:
        # q blocks strictly before this kv block's diagonal see nothing;
        # blocks at/after first_full_q are entirely below the diagonal and
        # skip mask compute.
        start_q = jnp.maximum(
            0, (ki * block_k - q_offset) // block_q)
        # clamp below at 0: for tq < tk (decode-style) the numerator goes
        # negative and python floor division would yield -1, starting the
        # UNMASKED loop at a phantom qi=-1 block
        first_full_q = jnp.minimum(
            num_q_blocks,
            jnp.maximum(0, (ki * block_k + block_k - 1 - q_offset
                            + block_q - 1) // block_q))

    def body(qi, carry, apply_mask):
        dk_acc, dv_acc = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :]
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :]
        lse2 = lse_ref[pl.ds(qi * block_q, block_q), :1] * _LOG2E
        dcor = dcor_ref[pl.ds(qi * block_q, block_q), :1]
        # s^T: [block_k, block_q] = (K*scale*log2e) Q^T, log2 domain
        st = jax.lax.dot_general(
            k_scaled, q_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if apply_mask:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            st = jnp.where(q_pos >= k_pos, st, _NEG_INF)
        pt = jnp.exp2(st - lse2.T)                # [block_k, block_q]
        # dv += P^T dO
        dv_acc = dv_acc + jax.lax.dot_general(
            pt.astype(cd), do_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp^T = V dO^T : [block_k, block_q]
        dpt = jax.lax.dot_general(
            v_blk, do_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dst = (pt * (dpt - dcor.T)).astype(cd)
        # dk += dS^T (Q*scale)  (the sm_scale factor rides on k_scaled's
        # partner: dK = scale * dS^T Q, and q_blk here is unscaled)
        dk_acc = dk_acc + jax.lax.dot_general(
            dst, q_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    carry = jax.lax.fori_loop(
        start_q, first_full_q, functools.partial(body, apply_mask=True),
        (jnp.zeros((k_scaled.shape[0], d), jnp.float32),
         jnp.zeros((k_scaled.shape[0], d), jnp.float32)))
    dk, dv = jax.lax.fori_loop(
        first_full_q, num_q_blocks,
        functools.partial(body, apply_mask=False), carry)
    dk_ref[...] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dcor_ref,
                            dk_ref, dv_ref, dq_ref, *, sm_scale: float,
                            causal: bool, block_q: int, block_k: int,
                            q_len: int, q_offset: int):
    """Single-pass backward: one grid cell = one kv block, computing its
    dK/dV AND this block's dQ contributions. The two-pass backward
    recomputes S twice (7 dots per q-kv pair); this computes it once
    (5 dots) and halves the Q/dO HBM traffic. dq is a REVISITED output
    ([q_len, D] f32, index ignoring ki): TPU pallas grids execute
    sequentially, so cell (g, ki) accumulates onto what (g, ki-1)
    wrote — the standard TPU revisiting-accumulator pattern.
    Refs: k/v/dk/dv [block_k, D]; q/do [q_len, D]; dq [q_len, D] f32;
    lse/dcor [q_len, LANES]."""
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    cd = k_ref.dtype
    k_scaled = (k_ref[...].astype(jnp.float32)
                * (sm_scale * _LOG2E)).astype(cd)
    k_raw = k_ref[...]
    v_blk = v_ref[...]
    d = k_scaled.shape[-1]

    num_q_blocks = pl.cdiv(q_len, block_q)
    start_q = 0
    first_full_q = 0
    if causal:
        start_q = jnp.maximum(0, (ki * block_k - q_offset) // block_q)
        # clamp below at 0 — see _flash_bwd_dkv_kernel: negative numerator
        # (tq < tk) must not start the unmasked loop at qi=-1
        first_full_q = jnp.minimum(
            num_q_blocks,
            jnp.maximum(0, (ki * block_k + block_k - 1 - q_offset
                            + block_q - 1) // block_q))

    def body(qi, carry, apply_mask):
        dk_acc, dv_acc = carry
        sl = pl.ds(qi * block_q, block_q)
        q_blk = q_ref[sl, :]
        do_blk = do_ref[sl, :]
        lse2 = lse_ref[sl, :1] * _LOG2E
        dcor = dcor_ref[sl, :1]
        st = jax.lax.dot_general(
            k_scaled, q_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # [block_k, block_q]
        if apply_mask:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            q_pos = q_offset + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            st = jnp.where(q_pos >= k_pos, st, _NEG_INF)
        pt = jnp.exp2(st - lse2.T)                # [block_k, block_q]
        dv_acc = dv_acc + jax.lax.dot_general(
            pt.astype(cd), do_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dpt = jax.lax.dot_general(
            v_blk, do_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # [block_k, block_q]
        dst = (pt * (dpt - dcor.T)).astype(cd)    # dS^T
        dk_acc = dk_acc + jax.lax.dot_general(
            dst, q_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dQ[q_blk] += scale * dS K  (dst^T @ K via contracting dim 0)
        dq_contrib = jax.lax.dot_general(
            dst, k_raw, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)   # [block_q, D]
        dq_ref[sl, :] = dq_ref[sl, :] + dq_contrib * sm_scale
        return dk_acc, dv_acc

    carry = jax.lax.fori_loop(
        start_q, first_full_q, functools.partial(body, apply_mask=True),
        (jnp.zeros((k_scaled.shape[0], d), jnp.float32),
         jnp.zeros((k_scaled.shape[0], d), jnp.float32)))
    dk, dv = jax.lax.fori_loop(
        first_full_q, num_q_blocks,
        functools.partial(body, apply_mask=False), carry)
    dk_ref[...] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _fused_bwd_enabled() -> bool:
    """Opt-in until profiled on real chips (RAY_TPU_FLASH_FUSED_BWD=1);
    interpret-mode tests pin its numerics against the two-pass path."""
    return os.environ.get("RAY_TPU_FLASH_FUSED_BWD", "0") == "1"


def _flash_bwd_fused_pallas(q, k, v, o, lse, do, causal: bool,
                            sm_scale: float, block_q: int, block_k: int,
                            interpret: bool):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    of = o.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    dof = do.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    dcor = jnp.broadcast_to(
        jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                keepdims=True),
        (b * h, tq, _LANES))
    kernel = functools.partial(
        _flash_bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, q_len=tq, q_offset=tk - tq)
    dkf, dvf, dqf = pl.pallas_call(
        kernel,
        grid=(b * h, pl.cdiv(tk, block_k)),
        in_specs=[
            pl.BlockSpec((None, tq, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, tq, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda g, i: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
            # dq: revisited across ki (index ignores i) — accumulator
            pl.BlockSpec((None, tq, d), lambda g, i: (g, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
            jax.ShapeDtypeStruct((b * h, tq, d), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_parallel_grid_params(2, interpret),
        cost_estimate=pl.CostEstimate(
            flops=10 * b * h * tq * tk * d,
            bytes_accessed=(qf.size + kf.size + vf.size + dof.size)
            * qf.dtype.itemsize,
            transcendentals=b * h * tq * tk),
    )(qf, kf, vf, dof, lse, dcor)
    dq = dqf.astype(q.dtype).reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    dk = dkf.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    dv = dvf.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


def _flash_bwd_pallas(q, k, v, o, lse, do, causal: bool, sm_scale: float,
                      block_q: int, block_k: int, interpret: bool):
    if _fused_bwd_enabled():
        return _flash_bwd_fused_pallas(q, k, v, o, lse, do, causal,
                                       sm_scale, block_q, block_k,
                                       interpret)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    of = o.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    dof = do.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    # softmax correction term D = rowsum(dO * O), lane-broadcast like lse
    dcor = jnp.broadcast_to(
        jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1,
                keepdims=True),
        (b * h, tq, _LANES))

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=tk, q_offset=tk - tq)
    dqf = pl.pallas_call(
        dq_kernel,
        grid=(b * h, pl.cdiv(tq, block_q)),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, tk, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, block_q, _LANES), lambda g, i: (g, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda g, i: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
        compiler_params=_parallel_grid_params(2, interpret),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * tq * tk * d,
            bytes_accessed=(qf.size + kf.size + vf.size + dof.size)
            * qf.dtype.itemsize,
            transcendentals=b * h * tq * tk),
    )(qf, kf, vf, dof, lse, dcor)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, q_len=tq, q_offset=tk - tq)
    dkf, dvf = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, pl.cdiv(tk, block_k)),
        in_specs=[
            pl.BlockSpec((None, tq, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, tq, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((None, tq, _LANES), lambda g, i: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda g, i: (g, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        interpret=interpret,
        compiler_params=_parallel_grid_params(2, interpret),
        cost_estimate=pl.CostEstimate(
            flops=6 * b * h * tq * tk * d,
            bytes_accessed=(qf.size + kf.size + vf.size + dof.size)
            * qf.dtype.itemsize,
            transcendentals=b * h * tq * tk),
    )(qf, kf, vf, dof, lse, dcor)

    dq = dqf.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    dk = dkf.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    dv = dvf.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


# ------------------------------------------------------------- dispatch


def _interpret_forced() -> bool:
    return os.environ.get("RAY_TPU_PALLAS_INTERPRET", "0") == "1"


def _use_pallas() -> bool:
    if os.environ.get("RAY_TPU_DISABLE_FLASH") == "1":  # ablation/debug escape hatch
        return False
    if _interpret_forced():
        return True
    if pltpu is None:
        return False
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


def _shapes_ok(q, k, block_q: int, block_k: int) -> bool:
    # Sequence lengths must divide the *effective* block size (after
    # clamping to the sequence length); otherwise the in-kernel pl.ds
    # reads would silently clamp out-of-bounds starts and corrupt the
    # causal indexing.
    tq, tk = q.shape[1], k.shape[1]
    return (tq % min(block_q, tq) == 0 and tk % min(block_k, tk) == 0
            and tq % 128 == 0 and tk % 128 == 0
            and (q.shape[-1] % 128 == 0 or q.shape[-1] == 64))


def set_default_blocks(block_q: Optional[int] = None,
                       block_k: Optional[int] = None) -> None:
    """Runtime override of the default flash block sizes — calls that
    did not pin block_q/block_k pick the new values up on their next
    trace (autotuning hook; bench.py sweeps these on chip)."""
    global DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K
    if block_q is not None:
        DEFAULT_BLOCK_Q = int(block_q)
    if block_k is not None:
        DEFAULT_BLOCK_K = int(block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None) -> jax.Array:
    """Fused attention. q,k,v: [batch, time, heads, head_dim] (kv time may
    differ). Pallas on TPU (fwd and bwd kernels); XLA reference elsewhere.
    block_q/block_k default to the module-level (env/autotune-settable)
    values at trace time.
    """
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k)[0]


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    block_q = DEFAULT_BLOCK_Q if block_q is None else block_q
    block_k = DEFAULT_BLOCK_K if block_k is None else block_k
    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    if _use_pallas() and _shapes_ok(q, k, block_q, block_k):
        out, lse = _flash_fwd_pallas(q, k, v, causal, scale, block_q,
                                     block_k, interpret=_interpret_forced())
        return out, (q, k, v, out, lse)
    out = mha_reference(q, k, v, causal, scale)
    return out, (q, k, v, None, None)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    block_q = DEFAULT_BLOCK_Q if block_q is None else block_q
    block_k = DEFAULT_BLOCK_K if block_k is None else block_k
    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    if lse is not None:
        return _flash_bwd_pallas(q, k, v, o, lse, g, causal, scale,
                                 block_q, block_k,
                                 interpret=_interpret_forced())

    def ref(q_, k_, v_):
        return mha_reference(q_, k_, v_, causal, scale)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
