"""Normalization layers as functional TPU-friendly ops.

fp32 statistics regardless of input dtype (bf16 activations on TPU), output
cast back — XLA fuses the whole thing into surrounding elementwise work, so
there is no Pallas kernel here on purpose: a hand-written layernorm would
only deny XLA the fusion with its neighbors.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-5) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x: jax.Array, scale: jax.Array,
             eps: float = 1e-6) -> jax.Array:
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(ms + eps)
            * scale.astype(jnp.float32)).astype(x.dtype)
