"""Normalization layers as functional TPU-friendly ops.

fp32 statistics regardless of input dtype (bf16 activations on TPU), output
cast back — XLA fuses the whole thing into surrounding elementwise work, so
there is no Pallas kernel here on purpose: a hand-written layernorm would
only deny XLA the fusion with its neighbors.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-5) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x: jax.Array, scale: jax.Array,
             eps: float = 1e-6) -> jax.Array:
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(ms + eps)
            * scale.astype(jnp.float32)).astype(x.dtype)


def lora_delta(h: jax.Array, a: jax.Array, b: jax.Array,
               scale: jax.Array) -> jax.Array:
    """Per-slot scatter-gathered LoRA contribution for a ragged decode
    batch: ``scale * (h @ A) @ B`` with a DIFFERENT adapter per batch
    row (the cross-tenant batched-decode matmul; serve/lora.py gathers
    A/B out of the adapter pool by each slot's adapter index before the
    call).

    ``h [B, t, d]``, ``a [B, d, r]``, ``b [B, r, o]``, ``scale [B]`` ->
    ``[B, o or t, o]`` in ``h.dtype``. fp32 accumulation like the base
    matmuls; rows whose adapter is the null slot (A == B == 0,
    scale == 0) contribute an exact-zero delta, so adding it to the base
    projection leaves those rows' values unchanged. Structured as the
    Pallas ragged-matmul kernel candidate (grouped by adapter index) the
    autotuner item will sweep — today it lowers to two batched einsums.
    """
    z = jnp.einsum("btd,bdr->btr", h, a,
                   preferred_element_type=jnp.float32)
    d = jnp.einsum("btr,bro->bto", z, b,
                   preferred_element_type=jnp.float32)
    return (d * scale[:, None, None]).astype(h.dtype)
