"""Ulysses-style sequence parallelism: all-to-all head<->sequence swap
over the `sp` mesh axis.

New capability relative to the reference, which has no sequence/context
parallelism in-tree (SURVEY.md §5.7). Where ring attention (see
ring_attention.py) rotates KV blocks around the ICI ring, Ulysses does two
`all_to_all`s: gather the full sequence while scattering heads, run plain
(flash) attention on H/sp full-length heads, then swap back. On TPU both
all_to_alls ride ICI; Ulysses moves 2x less data than the ring when
sp <= heads and composes with any attention kernel unchanged — the
standard trade (ring scales past head count, Ulysses doesn't).

Use inside shard_map with q,k,v sharded on the sequence axis:
    out = ulysses_attention(q, k, v, axis_name="sp")   # [B, T/sp, H, D]
"""
from __future__ import annotations

from typing import Callable, Optional

import jax

from .attention import mha_reference


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T/sp, H, D] -> [B, T, H/sp, D]: scatter heads, gather seq."""
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, T, H/sp, D] -> [B, T/sp, H, D]: inverse swap."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "sp", causal: bool = True,
                      sm_scale: Optional[float] = None,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """Exact attention over an sp-sharded sequence via head scattering.

    Per-shard shapes q,k,v: [B, T/sp, H, D]; H must be divisible by the
    `axis_name` mesh size. attn_fn(q, k, v, causal, sm_scale) defaults to
    the XLA reference; pass ops.attention.flash_attention for the Pallas
    kernel on TPU.
    """
    sp = jax.lax.psum(1, axis_name)
    h = q.shape[2]
    if h % sp != 0:
        raise ValueError(f"heads {h} not divisible by sp axis size {sp}")
    fn = attn_fn or mha_reference
    qg, kg, vg = (_seq_to_heads(t, axis_name) for t in (q, k, v))
    out = fn(qg, kg, vg, causal, sm_scale)     # [B, T, H/sp, D]
    return _heads_to_seq(out, axis_name)       # [B, T/sp, H, D]


__all__ = ["ulysses_attention"]
