"""Ring attention: exact attention over sequences sharded on the `sp` mesh
axis, with blockwise KV rotation via `jax.lax.ppermute`.

New capability relative to the reference, which has no sequence/context
parallelism anywhere in-tree (SURVEY.md §5.7). Design: each sp rank holds a
[B, T/sp, H, D] shard of q/k/v; KV shards rotate around the ICI ring for sp
steps while every rank accumulates its queries' attention with an online
(flash-style) softmax. XLA overlaps the `ppermute` with the local block's
compute, so at the steady state the ring transfer is hidden behind the MXU
work — the same overlap structure the Pallas guide's ring-collective
pattern expresses at kernel level.

Causal masking across blocks: rank i's queries occupy global positions
[i*T_blk, (i+1)*T_blk); the KV block arriving at step s originates from
rank (i - s) mod sp. Blocks wholly in the future contribute nothing and
are skipped via masking (their logits are -inf; `where` keeps the math
numerically safe).

Use inside shard_map/pjit with q,k,v already sharded on axis `axis_name`:
    out = ring_attention(q, k, v, axis_name="sp", causal=True)
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attend(q, k, v, mask, sm_scale):
    """Flash-style block contribution. q: [B,Tq,H,D], k/v: [B,Tk,H,D],
    mask: [Tq,Tk] bool or None. Returns (m, l, acc) partials in fp32."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)              # [B,H,Tq,1]
    p = jnp.exp(s - m)  # fully-masked blocks are zeroed by alpha_cur below
    l = jnp.sum(p, axis=-1, keepdims=True)              # [B,H,Tq,1]
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "sp", causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Exact attention over an sp-sharded sequence. Shapes per shard:
    q,k,v [B, T_blk, H, D]; returns [B, T_blk, H, D]."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    sp = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    t_blk = q.shape[1]
    b, _, h, d = q.shape

    q32 = q.astype(jnp.float32)
    m0 = jnp.full((b, h, t_blk, 1), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, t_blk, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((b, h, t_blk, d), dtype=jnp.float32)

    def step(carry, s):
        m_prev, l_prev, acc, k_cur, v_cur = carry
        src = (rank - s) % sp  # origin rank of the kv block now held
        if causal:
            # intra-block causal mask only applies on the diagonal block
            qpos = rank * t_blk + jax.lax.broadcasted_iota(
                jnp.int32, (t_blk, t_blk), 0)
            kpos = src * t_blk + jax.lax.broadcasted_iota(
                jnp.int32, (t_blk, t_blk), 1)
            mask = qpos >= kpos
        else:
            mask = jnp.ones((t_blk, t_blk), dtype=bool)
        m_cur, l_cur, acc_cur = _block_attend(q32, k_cur, v_cur, mask,
                                              sm_scale)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha_prev = jnp.exp(jnp.maximum(m_prev, _NEG_INF) - m_new)
        alpha_cur = jnp.exp(jnp.maximum(m_cur, _NEG_INF) - m_new)
        l_new = alpha_prev * l_prev + alpha_cur * l_cur
        acc_new = alpha_prev * acc + alpha_cur * acc_cur
        # rotate kv to the next rank; XLA overlaps this with the block math
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(sp))
    out = acc / jnp.maximum(l, 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B,T,H,D]
