"""Fused linear + cross-entropy Pallas kernel for TPU.

The LM-head loss `CE(x @ W^T, targets)` is the memory hog of LM training:
at GPT-2 vocab the fp32 logits are ~200KB *per token row*, so a
materialized [N, V] logits tensor plus log_softmax costs gigabytes of HBM
traffic per step. This kernel never materializes logits: the vocab axis
streams through VMEM in blocks while an online logsumexp (flash-attention
style, log2 domain) and the target-logit pick run in registers. The
backward recomputes P = exp(logits - lse) blockwise from the saved
row-logsumexp — two kernels (dx over row blocks, dW over vocab blocks) —
with the one-hot terms (wte gather / segment-sum scatter) left to XLA
where they are cheap single passes.

New capability vs the reference (no kernels of its own — SURVEY.md §5.7);
the chunked-XLA fallback (`_ce_reference`) is the correctness oracle, and
interpret-mode tests drive the kernels on CPU CI.
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 8
_NEG_INF = -1e30
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453

# token rows per program (tuned on v5e; env override for bench sweeps)
DEFAULT_BLOCK_N = int(os.environ.get("RAY_TPU_CE_BLOCK_N", "1024"))


def _ce_reference(x: jax.Array, w: jax.Array, targets: jax.Array,
                  vocab_size: int) -> Tuple[jax.Array, jax.Array]:
    """XLA reference: per-row loss and logsumexp. x [N,d], w [V,d]."""
    logits = jnp.dot(x, w.T, preferred_element_type=jnp.float32)
    if w.shape[0] != vocab_size:
        col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(col < vocab_size, logits, _NEG_INF)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[:, None], axis=1)[:, 0]
    return lse - tgt, lse


# --------------------------------------------------------------- forward


def _ce_fwd_kernel(x_ref, w_ref, t_ref, loss_ref, lse_ref,
                   m_scr, l_scr, tgt_scr, *, block_n: int, block_v: int,
                   n_v_blocks: int, vocab_size: int, padded: bool):
    """Grid (row_block, vocab_block), vocab minor. Scratch carries the
    online (m, l, target-logit) state across vocab steps; the final step
    writes loss and lse. All logits math is log2-domain."""
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_scr[...] = jnp.full((block_n, 1), _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros((block_n, 1), jnp.float32)
        tgt_scr[...] = jnp.zeros((block_n, 1), jnp.float32)

    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * _LOG2E  # [block_n, block_v]
    col = vi * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)
    if padded:  # mask vocab padding rows of w
        s = jnp.where(col < vocab_size, s, _NEG_INF)
    tgt = t_ref[...]  # [block_n, 1] int32
    tgt_here = jnp.sum(jnp.where(col == tgt, s, 0.0), axis=-1,
                       keepdims=True)
    tgt_scr[...] = tgt_scr[...] + tgt_here

    m_prev = m_scr[...]
    l_prev = l_scr[...]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p_sum = jnp.sum(jnp.exp2(s - m_new), axis=-1, keepdims=True)
    l_new = jnp.exp2(m_prev - m_new) * l_prev + p_sum
    m_scr[...] = m_new
    l_scr[...] = l_new

    @pl.when(vi == n_v_blocks - 1)
    def _finalize():
        lse2 = m_scr[...] + jnp.log2(jnp.maximum(l_scr[...], 1e-30))
        lse_nat = lse2 * _LN2
        loss = lse_nat - tgt_scr[...] * _LN2
        loss_ref[...] = jnp.broadcast_to(loss, (block_n, _LANES))
        lse_ref[...] = jnp.broadcast_to(lse_nat, (block_n, _LANES))


def _ce_fwd_pallas(x, w, targets, vocab_size: int, block_n: int,
                   block_v: int, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    n, d = x.shape
    v = w.shape[0]
    block_n = min(block_n, n)
    grid = (pl.cdiv(n, block_n), v // block_v)
    t2 = targets.astype(jnp.int32).reshape(n, 1)
    kernel = functools.partial(
        _ce_fwd_kernel, block_n=block_n, block_v=block_v,
        n_v_blocks=v // block_v, vocab_size=vocab_size,
        padded=v > vocab_size)
    loss, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * n * v * d,
            bytes_accessed=(x.size * x.dtype.itemsize
                            + pl.cdiv(n, block_n) * w.size
                            * w.dtype.itemsize),
            transcendentals=n * v),
    )(x, w, t2)
    return loss[:, 0], lse[:, 0]


# -------------------------------------------------------------- backward


def _ce_dx_kernel(x_ref, w_ref, lse_ref, dx_ref, acc_scr, *,
                  block_n: int, block_v: int, n_v_blocks: int,
                  vocab_size: int, padded: bool):
    """dx_unscaled = P @ W, streamed over vocab blocks. Grid
    (row_block, vocab_block), vocab minor; acc in scratch."""
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    cd = x_ref.dtype
    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * _LOG2E
    if padded:
        col = vi * block_v + jax.lax.broadcasted_iota(
            jnp.int32, (s.shape[0], block_v), 1)
        s = jnp.where(col < vocab_size, s, _NEG_INF)
    lse2 = lse_ref[:, :1] * _LOG2E
    p = jnp.exp2(s - lse2)
    acc_scr[...] = acc_scr[...] + jax.lax.dot_general(
        p.astype(cd), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(vi == n_v_blocks - 1)
    def _finalize():
        dx_ref[...] = acc_scr[...].astype(dx_ref.dtype)


def _ce_dw_kernel(x_ref, w_ref, lse_ref, xg_ref, dw_ref, acc_scr, *,
                  block_n: int, block_v: int, n_n_blocks: int,
                  vocab_size: int, padded: bool):
    """dW_unscaled[v_block] = P^T @ (g*x), streamed over row blocks. Grid
    (vocab_block, row_block), rows minor."""
    ni = pl.program_id(1)
    vi = pl.program_id(0)

    @pl.when(ni == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    cd = x_ref.dtype
    x = x_ref[...]
    w = w_ref[...]
    st = jax.lax.dot_general(  # [block_v, block_n] = W X^T, log2 domain
        w, x, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * _LOG2E
    if padded:
        row = vi * block_v + jax.lax.broadcasted_iota(
            jnp.int32, (block_v, st.shape[1]), 0)
        st = jnp.where(row < vocab_size, st, _NEG_INF)
    lse2 = lse_ref[:, :1] * _LOG2E  # [block_n, 1]
    pt = jnp.exp2(st - lse2.T)      # [block_v, block_n]
    acc_scr[...] = acc_scr[...] + jax.lax.dot_general(
        pt.astype(cd), xg_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ni == n_n_blocks - 1)
    def _finalize():
        dw_ref[...] = acc_scr[...].astype(dw_ref.dtype)


def _ce_bwd_pallas(x, w, targets, lse, g, vocab_size: int, block_n: int,
                   block_v: int, interpret: bool):
    from jax.experimental.pallas import tpu as pltpu

    n, d = x.shape
    v = w.shape[0]
    block_n = min(block_n, n)
    lse_b = jnp.broadcast_to(lse[:, None], (n, _LANES))

    dx_kernel = functools.partial(
        _ce_dx_kernel, block_n=block_n, block_v=block_v,
        n_v_blocks=v // block_v, vocab_size=vocab_size,
        padded=v > vocab_size)
    dx_unscaled = pl.pallas_call(
        dx_kernel,
        grid=(pl.cdiv(n, block_n), v // block_v),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, _LANES), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * n * v * d, bytes_accessed=2 * x.size,
            transcendentals=n * v),
    )(x, w, lse_b)
    # one-hot term and upstream scaling in XLA (cheap single passes)
    dx = (dx_unscaled - w[targets].astype(jnp.float32)) * g[:, None]

    xg = (x.astype(jnp.float32) * g[:, None]).astype(x.dtype)
    dw_kernel = functools.partial(
        _ce_dw_kernel, block_n=block_n, block_v=block_v,
        n_n_blocks=pl.cdiv(n, block_n), vocab_size=vocab_size,
        padded=v > vocab_size)
    dw_unscaled = pl.pallas_call(
        dw_kernel,
        grid=(v // block_v, pl.cdiv(n, block_n)),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            pl.BlockSpec((block_n, _LANES), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((v, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * n * v * d,
            bytes_accessed=2 * x.size + w.size, transcendentals=n * v),
    )(x, w, lse_b, xg)
    # scatter-add of the one-hot rows: dW[tgt] -= g*x
    dw = dw_unscaled.at[targets].add(-xg.astype(jnp.float32))
    return dx.astype(x.dtype), dw.astype(w.dtype)


# ------------------------------------------------------------- dispatch


def _interpret_forced() -> bool:
    return os.environ.get("RAY_TPU_PALLAS_INTERPRET", "0") == "1"


def _use_pallas() -> bool:
    if os.environ.get("RAY_TPU_DISABLE_FUSED_CE") == "1":  # ablation/debug escape hatch
        return False
    if _interpret_forced():
        return True
    try:
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _pick_block_v(v: int) -> Optional[int]:
    for bv in (512, 448, 384, 320, 256, 128):
        if v % bv == 0:
            return bv
    return None


def fused_ce_supported(n: int, d: int, v: int) -> bool:
    """True iff the Pallas fused path will actually run for these shapes
    on this backend — callers (models.gpt2) dispatch on this so a shape
    miss falls back to *their* chunked path, never the unchunked
    full-logit reference."""
    return (_use_pallas() and _pick_block_v(v) is not None
            and n % min(DEFAULT_BLOCK_N, n) == 0 and n % 128 == 0
            and d % 128 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def linear_cross_entropy(x: jax.Array, w: jax.Array, targets: jax.Array,
                         vocab_size: int) -> jax.Array:
    """Per-row CE loss of logits = x @ w.T without materializing logits.

    x [N, d], w [V, d] (rows >= vocab_size are padding and masked),
    targets [N] int. Returns f32 [N]. Pallas fused kernel on TPU; chunk-
    free XLA reference elsewhere.
    """
    return _lce_fwd(x, w, targets, vocab_size)[0]


def _lce_fwd(x, w, targets, vocab_size):
    n, d = x.shape
    v = w.shape[0]
    use = fused_ce_supported(n, d, v)
    if use:
        loss, lse = _ce_fwd_pallas(x, w, targets, vocab_size,
                                   DEFAULT_BLOCK_N, _pick_block_v(v),
                                   _interpret_forced())
    else:
        loss, lse = _ce_reference(x, w, targets, vocab_size)
    return loss, (x, w, targets, lse, use)


def _lce_bwd(vocab_size, res, g):
    x, w, targets, lse, used_pallas = res
    if used_pallas:
        bv = _pick_block_v(w.shape[0])
        dx, dw = _ce_bwd_pallas(x, w, targets, lse, g, vocab_size,
                                DEFAULT_BLOCK_N, bv, _interpret_forced())
        return dx, dw, None
    # XLA fallback: differentiate the reference
    def ref(x_, w_):
        return _ce_reference(x_, w_, targets, vocab_size)[0]

    _, vjp = jax.vjp(ref, x, w)
    dx, dw = vjp(g)
    return dx, dw, None


linear_cross_entropy.defvjp(_lce_fwd, _lce_bwd)
