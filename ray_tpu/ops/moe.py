"""Mixture-of-Experts FFN with expert parallelism over the `ep` mesh axis.

New capability relative to the reference — Ray has no EP/MoE support
in-tree (SURVEY.md §2.3). Design follows the GShard/Switch recipe shaped
for TPU: static capacity (no dynamic shapes — XLA needs fixed tiles for
the MXU), dispatch/combine as einsums (MXU-friendly one-hot matmuls), and
`jax.lax.all_to_all` over the `ep` axis to exchange token shards between
expert shards, riding ICI.

Data layout inside shard_map over `ep`:
  tokens  x: [T, D]            (local shard of the batch*seq tokens)
  experts  : E total, E/ep held locally as w_in [E_l, D, F], w_out [E_l, F, D]
  dispatch : [T, E, C] one-hot → einsum → [E, C, D]
  all_to_all: [ep, E_l, C, D] swap axis0 ↔ ep ranks → local experts now
              hold every rank's C-slot block: [E_l, ep*C, D]
  expert FFN, then the inverse all_to_all + combine einsum.

Top-k routing with normalized probs; tokens overflowing an expert's
capacity are dropped (their combine weight is 0 — standard Switch
behavior; raise capacity_factor to trade memory for fewer drops).
"""
from __future__ import annotations

import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp


def _top_k_routing(logits: jax.Array, top_k: int, num_experts: int,
                   capacity: int):
    """Returns (dispatch [T,E,C] bool-ish float, combine [T,E,C] float)."""
    t = logits.shape[0]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]
    topv, topi = jax.lax.top_k(probs, top_k)                     # [T, k]
    topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)

    # expert one-hots per choice: [k, T, E]
    onehot = jax.nn.one_hot(topi.T, num_experts, dtype=jnp.float32)
    # position of each (choice, token) in its expert's queue — cumulative
    # count over choices-major, token-minor order (GShard ordering)
    flat = onehot.reshape(top_k * t, num_experts)
    pos = jnp.cumsum(flat, axis=0) - flat                        # [k*T, E]
    pos = pos.reshape(top_k, t, num_experts)
    in_cap = (pos < capacity).astype(jnp.float32) * onehot
    pos_idx = jnp.einsum("kte,kte->kt", pos, onehot).astype(jnp.int32)
    cap_onehot = jax.nn.one_hot(jnp.clip(pos_idx, 0, capacity - 1),
                                capacity, dtype=jnp.float32)     # [k,T,C]
    disp_k = jnp.einsum("kte,ktc->ktec", in_cap, cap_onehot)     # [k,T,E,C]
    dispatch = disp_k.sum(0)                                     # [T,E,C]
    combine = jnp.einsum("ktec,kt->tec", disp_k, topv.T)
    return dispatch, combine


def moe_ffn(x: jax.Array, gate_w: jax.Array, w_in: jax.Array,
            w_out: jax.Array, *, top_k: int = 2,
            capacity_factor: float = 1.25,
            axis_name: Optional[str] = None,
            activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu,
            return_router_logits: bool = False):
    """MoE feed-forward. x: [T, D] (or [B, S, D], flattened internally).

    gate_w: [D, E]. With axis_name=None (single shard): w_in [E, D, F],
    w_out [E, F, D]. Under shard_map over `axis_name`: w_in [E/ep, D, F],
    w_out [E/ep, F, D] — the local expert shard — and tokens are exchanged
    with all_to_all.

    With return_router_logits=True, returns (y, logits[T, E]) so the caller
    can feed load_balancing_loss without recomputing the gate matmul.
    """
    orig_shape = x.shape
    if x.ndim == 3:
        x = x.reshape(-1, x.shape[-1])
    t, d = x.shape

    ep = 1 if axis_name is None else jax.lax.psum(1, axis_name)
    e_local = w_in.shape[0]
    e = e_local * ep
    capacity = max(1, math.ceil(top_k * t * capacity_factor / e))

    logits = x @ gate_w.astype(x.dtype)                          # [T, E]
    dispatch, combine = _top_k_routing(logits, top_k, e, capacity)

    xin = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    if axis_name is not None:
        # [E, C, D] -> [ep, E_l, C, D]; swap the leading block axis across
        # ranks so each rank holds all source ranks' slots for its experts
        xin = xin.reshape(ep, e_local, capacity, d)
        xin = jax.lax.all_to_all(xin, axis_name, split_axis=0,
                                 concat_axis=0, tiled=False)
        xin = xin.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)

    xin = xin.astype(x.dtype)
    h = activation(jnp.einsum("ecd,edf->ecf", xin, w_in))
    out = jnp.einsum("ecf,efd->ecd", h, w_out)                   # [E_l,·,D]

    if axis_name is not None:
        out = out.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
        out = jax.lax.all_to_all(out, axis_name, split_axis=0,
                                 concat_axis=0, tiled=False)
        out = out.reshape(e, capacity, d)

    y = jnp.einsum("tec,ecd->td", combine,
                   out.astype(jnp.float32)).astype(x.dtype)
    y = y.reshape(orig_shape)
    if return_router_logits:
        return y, logits
    return y


def load_balancing_loss(logits: jax.Array, top_k: int = 2) -> jax.Array:
    """Switch-style auxiliary loss: E * sum_e(frac_tokens_e * mean_prob_e).
    logits: [..., T, E] router logits."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    e = probs.shape[-1]
    _, topi = jax.lax.top_k(probs, top_k)
    counts = jax.nn.one_hot(topi, e, dtype=jnp.float32).sum(-2)  # [..., k→E]
    frac = counts.reshape(-1, e).mean(0) / top_k
    mean_prob = probs.reshape(-1, e).mean(0)
    return e * jnp.sum(frac * mean_prob)


__all__ = ["moe_ffn", "load_balancing_loss"]
