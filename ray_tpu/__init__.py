"""ray_tpu: a TPU-native distributed AI framework.

Capability surface of the reference Ray runtime (tasks, actors, objects, gang
scheduling, fault tolerance + the Data/Train/Tune/Serve/RLlib libraries),
re-designed TPU-first: a single conductor control plane with slice-aware
resources, direct worker-to-worker task push, shared-memory host objects, and
JAX/XLA/pjit/Pallas for everything on-device (see ray_tpu.parallel,
ray_tpu.models, ray_tpu.train, ...).

Public core API mirrors /root/reference/python/ray/_private/worker.py:
init :1214, get :2523, put :2655, wait :2720, kill :2901.
"""
from __future__ import annotations

import atexit
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from . import exceptions  # noqa: F401
from ._private import worker as _worker_mod
from ._private.conductor import Conductor
from ._private.object_store import ObjectRef  # noqa: F401
from ._private.worker import Worker
from .actor import ActorClass, ActorHandle, exit_actor, get_actor  # noqa: F401
from .remote_function import RemoteFunction

__version__ = "0.1.0"

_conductor: Optional[Conductor] = None
_system_config_prior: Optional[Dict[str, Optional[str]]] = None


def is_initialized() -> bool:
    return _worker_mod.global_worker is not None


def init(address: Optional[str] = None, *,
         num_cpus: Optional[int] = None,
         resources: Optional[Dict[str, float]] = None,
         namespace: str = "default",
         session_dir: Optional[str] = None,
         worker_env: Optional[Dict[str, str]] = None,
         ignore_reinit_error: bool = False,
         _system_config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Start a local cluster (conductor in-process) or connect to an existing
    one via ``address="host:port"``.

    ``_system_config`` overrides flags from the central table
    (``ray_tpu._private.config``) — reference semantics of ray.init's
    _system_config over ray_config_def.h."""
    global _conductor, _system_config_prior
    if _system_config:
        from ._private.config import config as _cfg

        _system_config_prior = _cfg.apply(_system_config)
    if is_initialized():
        if ignore_reinit_error:
            return {"address": _worker_mod.global_worker.conductor_address}
        raise RuntimeError("ray_tpu.init() already called; "
                           "use ignore_reinit_error=True to ignore")
    if isinstance(address, str) and address.startswith("ray://"):
        # Ray-Client mode (reference python/ray/util/client): one
        # outbound connection to the head's ClientProxy, the whole
        # public API routed through a server-side driver.
        from .client import connect

        _worker_mod.global_worker = connect(address[len("ray://"):])
        return {"address": _worker_mod.global_worker.conductor_address,
                "client": True}
    if address == "auto":
        # Reference semantics of ray.init("auto") / RAY_ADDRESS.
        address = os.environ.get("RAY_TPU_ADDRESS")
        if not address:
            raise RuntimeError(
                "no RAY_TPU_ADDRESS in the environment; pass "
                "address='host:port' or start a head with "
                "`python -m ray_tpu start --head`")
    elif address is None:
        # Job drivers spawned by the head's JobManager find their cluster
        # here (reference: RAY_ADDRESS).
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    if session_dir is None:
        # must be unique per cluster: a reused dir would make the new
        # conductor restore the PREVIOUS cluster's persistence snapshot
        import uuid as _uuid

        session_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu",
            f"session_{time.strftime('%Y%m%d-%H%M%S')}_{os.getpid()}"
            f"_{_uuid.uuid4().hex[:8]}")
    os.makedirs(session_dir, exist_ok=True)

    if address is None:
        total: Dict[str, float] = dict(resources or {})
        total.setdefault("CPU", float(num_cpus if num_cpus is not None
                                      else (os.cpu_count() or 1)))
        tpus = _detect_tpu_chips()
        if tpus and "TPU" not in total:
            total["TPU"] = float(tpus)
        # Workers must not grab the (single-client) TPU runtime: the driver
        # owns the chips; tasks needing device access use the driver-held
        # mesh (ray_tpu.parallel) or explicit TPU-resource actors.
        wenv = {"JAX_PLATFORMS": "cpu"}
        import sys as _sys

        wenv["RAY_TPU_DRIVER_SYS_PATH"] = os.pathsep.join(
            p for p in _sys.path if p and os.path.isdir(p))
        wenv.update(worker_env or {})
        _conductor = Conductor(total, session_dir, worker_env=wenv).start()
        conductor_address = _conductor.address
        # Pre-start workers so first tasks don't pay process cold-start
        # (reference: WorkerPool prestarts language workers, worker_pool.h:156)
        _conductor.handler.prestart_workers(min(int(total.get("CPU", 1)), 4))
    else:
        host, port = address.rsplit(":", 1)
        conductor_address = (host, int(port))

    w = Worker(mode="driver", conductor_address=conductor_address,
               session_dir=session_dir)
    _worker_mod.global_worker = w
    # metrics registered before a prior shutdown() stopped the push loop
    # must resume flowing to THIS cluster's conductor
    try:
        from .util.metrics import _registry as _metrics_registry

        if _metrics_registry._metrics:
            _metrics_registry._ensure_pusher()
    except Exception:  # noqa: BLE001 — metrics are never init-fatal
        pass
    atexit.register(shutdown)
    return {"address": conductor_address, "session_dir": session_dir}


def _detect_tpu_chips() -> int:
    """TPU chip detection — analog of the reference's
    python/ray/_private/accelerators/tpu.py:102-119 (reads /dev/accel* and
    GCE metadata). Here: env override, /dev/accel*, then the axon platform."""
    if os.environ.get("RAY_TPU_CHIPS"):
        return int(os.environ["RAY_TPU_CHIPS"])
    import glob

    accels = glob.glob("/dev/accel*")
    if accels:
        return len(accels)
    if "axon" in os.environ.get("JAX_PLATFORMS", "") or \
            "tpu" in os.environ.get("JAX_PLATFORMS", ""):
        return 1
    return 0


def shutdown() -> None:
    global _conductor, _system_config_prior
    w = _worker_mod.global_worker
    if w is not None:
        # metrics first: the final registry flush needs the conductor
        # connection the worker shutdown is about to close
        try:
            from .util import metrics as _metrics

            _metrics.shutdown()
        except Exception:  # noqa: BLE001 — never block shutdown
            pass
        try:
            # cached weight publishers hold chunk refs against this
            # worker's store; drop them with the cluster they fed.
            # sys.modules check: never IMPORT the fabric (and jax with
            # it) just to shut down a process that never published.
            import sys as _sys

            pub_mod = _sys.modules.get("ray_tpu.weights.publisher")
            if pub_mod is not None:
                pub_mod._reset_publishers()
        except Exception:  # noqa: BLE001 — never block shutdown
            pass
        w.shutdown()
        _worker_mod.global_worker = None
    if _conductor is not None:
        _conductor.stop()
        _conductor = None
    # SIGKILL'ed workers (chaos tests, OOM kills) cannot unlink their shm
    # arena segments; left behind they hold tmpfs RAM across runs. The
    # conductor's stop() sweeps its own session — this covers connects
    # to remote clusters and anything that died since.
    try:
        from ._private.object_store import cleanup_leaked_segments

        cleanup_leaked_segments()
    except Exception:  # noqa: BLE001 — never block shutdown
        pass
    if _system_config_prior is not None:
        # this cluster's _system_config env exports must not leak into
        # the next cluster started in this process
        from ._private.config import config as _cfg

        _cfg.restore(_system_config_prior)
        _system_config_prior = None


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes (reference
    python/ray/_private/worker.py `remote`)."""
    if len(args) == 1 and not kwargs and callable(args[0]):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("remote() takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")

    def wrap(fn_or_cls):
        return _make_remote(fn_or_cls, kwargs)

    return wrap


def _make_remote(fn_or_cls, options: Dict[str, Any]):
    if isinstance(fn_or_cls, type):
        return ActorClass(fn_or_cls, options)
    return RemoteFunction(fn_or_cls, options)


def put(value: Any) -> ObjectRef:
    return _require_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        timeout: Optional[float] = None):
    return _require_worker().get(refs, timeout=timeout)


async def get_async(ref: ObjectRef):
    """Await an ObjectRef from asyncio code without blocking the loop
    (reference: `await ref` support, python/ray/_private/async_compat)."""
    return await _require_worker().get_async(ref)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True
         ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    return _require_worker().wait(refs, num_returns=num_returns,
                                  timeout=timeout, fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    w = _require_worker()
    w.conductor.call("kill_actor", actor.actor_id, no_restart, timeout=30.0)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Cancel the task or actor call producing `ref`.

    Queued work is dropped; running work is interrupted cooperatively
    (force=True kills the executing worker — the guaranteed stop).
    Subsequent get(ref) raises TaskCancelledError. Best-effort like the
    reference: a non-force cancel cannot interrupt native code until it
    re-enters the interpreter."""
    _require_worker().cancel(ref, force=force)


def cluster_resources() -> Dict[str, float]:
    return _require_worker().conductor.call("cluster_resources", timeout=30.0)


def available_resources() -> Dict[str, float]:
    return _require_worker().conductor.call("available_resources",
                                            timeout=30.0)


def nodes() -> List[Dict[str, Any]]:
    return _require_worker().conductor.call("nodes", timeout=30.0)


def _require_worker() -> Worker:
    w = _worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    return w


class _RuntimeContext:
    @property
    def worker_id(self) -> str:
        return _require_worker().worker_id

    @property
    def job_id(self) -> str:
        return _require_worker().job_id

    @property
    def is_driver(self) -> bool:
        return _require_worker().mode == "driver"

    @property
    def actor_id(self) -> Optional[str]:
        rt = _require_worker()._actor_runtime
        return rt.actor_id if rt else None

    def get_actor_handle(self) -> Optional[ActorHandle]:
        w = _require_worker()
        rt = w._actor_runtime
        if rt is None:
            return None
        return ActorHandle(rt.actor_id, w.address)


def get_runtime_context() -> _RuntimeContext:
    return _RuntimeContext()


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "put", "get",
    "get_async", "wait",
    "kill", "cancel", "get_actor", "exit_actor", "cluster_resources",
    "available_resources", "nodes", "get_runtime_context", "ObjectRef",
    "ActorClass", "ActorHandle", "exceptions", "__version__",
]
