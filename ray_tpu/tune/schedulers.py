"""Trial schedulers: FIFO, ASHA (async successive halving), median
stopping, HyperBand, and Population Based Training.

API surface of the reference's python/ray/tune/schedulers/ —
`async_hyperband.py` (ASHA), `median_stopping_rule.py`, `hyperband.py`,
`pbt.py` — reduced to the decision protocol the controller consumes:
on_trial_result -> CONTINUE | STOP | PAUSE, plus PBT's exploit directive
carried on the scheduler object (the controller applies checkpoint
transfer + config mutation; see tuner.py).
"""
from __future__ import annotations

import math
import random
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if getattr(self, "metric", None) is None and metric:
            self.metric = metric
        if mode:
            self.mode = getattr(self, "mode", None) or mode

    def on_trial_add(self, trial_id: str) -> None:
        pass

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        pass

    def exploit_directive(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """PBT hook: non-None => controller should clone src trial's
        checkpoint into trial_id with the given config."""
        return None

    def on_trials_paused(self, trial_ids: List[str]) -> None:
        """Synch-barrier hook: the controller calls this when every live
        trial has either PAUSEd or terminated. The scheduler may queue
        exploit directives; the controller then resumes all paused
        trials (reference pbt.py synch=True mode)."""

    def resume_decision(self, trial_id: str) -> str:
        """Barrier follow-up: after on_trials_paused, the controller asks
        per paused trial whether to resume (CONTINUE) or halt (STOP) —
        how synchronous HyperBand halves a rung (reference hyperband.py
        cur_band promotion)."""
        return CONTINUE


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference trial_scheduler.py)."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference schedulers/async_hyperband.py): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung is stopped
    unless it is in the top 1/reduction_factor of results recorded there."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t, self.grace_period = max_t, grace_period
        self.rf = reduction_factor
        self._rungs: Dict[int, List[float]] = defaultdict(list)
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(int(t))
            t *= reduction_factor
        self._milestones = milestones
        self._next_milestone: Dict[str, int] = {}

    def on_trial_add(self, trial_id: str) -> None:
        self._next_milestone[trial_id] = 0

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        idx = self._next_milestone.get(trial_id, 0)
        if idx >= len(self._milestones) or t < self._milestones[idx]:
            return CONTINUE
        milestone = self._milestones[idx]
        self._next_milestone[trial_id] = idx + 1
        score = self._score(result)
        rung = self._rungs[milestone]
        rung.append(score)
        if len(rung) < self.rf:
            return CONTINUE  # not enough evidence yet
        cutoff_rank = max(1, int(len(rung) / self.rf))
        cutoff = sorted(rung, reverse=True)[cutoff_rank - 1]
        return CONTINUE if score >= cutoff else STOP


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference schedulers/hyperband.py): trials
    are dealt round-robin into brackets; bracket `s` starts its rungs at
    r0 = max_t / rf^s. Every trial PAUSEs at its bracket's current rung
    milestone; when the whole population is paused (the controller's
    synch barrier), each rung is halved — the top 1/rf of the bracket's
    scores resume toward the next rung, the rest STOP at
    resume_decision. Unlike ASHA (AsyncHyperBandScheduler), a decision
    always compares the FULL rung, so no trial is stopped against a
    partial population — the bracket semantics the async variant trades
    away for utilization."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t, self.rf = max_t, reduction_factor
        import math

        s_max = max(0, int(math.log(max(max_t / grace_period, 1))
                           / math.log(reduction_factor)))
        # bracket s: first rung at max_t / rf^s, then *rf per rung
        self._bracket_r0 = [max(int(max_t / reduction_factor ** s), 1)
                            for s in range(s_max + 1)]
        # band sizing (reference hyperband.py): bracket s admits
        # n_s = ceil((s_max+1)/(s+1) * rf^s) trials, filled
        # most-aggressive-first (largest s = smallest starting budget);
        # when a band is full a fresh band opens
        import math as _math

        self._quota = [int(_math.ceil((s_max + 1) / (s + 1)
                                      * reduction_factor ** s))
                       for s in range(s_max + 1)]
        self._fill_order = list(range(s_max, -1, -1))
        self._fill_counts = [0] * (s_max + 1)
        self._bracket_of: Dict[str, int] = {}
        self._rung_idx: Dict[str, int] = {}     # trial -> rungs passed
        self._last_score: Dict[str, float] = {}
        self._paused_at: Dict[str, int] = {}    # trial -> milestone
        self._halted: set = set()
        self._done: set = set()

    def _milestone(self, trial_id: str) -> int:
        b = self._bracket_of[trial_id]
        r0 = self._bracket_r0[b]
        return min(self.max_t,
                   int(r0 * self.rf ** self._rung_idx[trial_id]))

    def on_trial_add(self, trial_id: str) -> None:
        for s in self._fill_order:
            if self._fill_counts[s] < self._quota[s]:
                break
        else:  # band full: open a new one
            self._fill_counts = [0] * len(self._quota)
            s = self._fill_order[0]
        self._fill_counts[s] += 1
        self._bracket_of[trial_id] = s
        self._rung_idx[trial_id] = 0

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        self._last_score[trial_id] = self._score(result)
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        if t >= self._milestone(trial_id):
            self._paused_at[trial_id] = self._milestone(trial_id)
            return PAUSE
        return CONTINUE

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        self._done.add(trial_id)
        self._paused_at.pop(trial_id, None)

    def on_trials_paused(self, trial_ids: List[str]) -> None:
        """The halving step: group paused trials by (bracket, milestone)
        and keep each group's top 1/rf; everyone else is halted at
        resume_decision."""
        groups: Dict[tuple, List[str]] = defaultdict(list)
        for tid in trial_ids:
            if tid in self._paused_at and tid not in self._done:
                groups[(self._bracket_of[tid],
                        self._paused_at[tid])].append(tid)
        for (_b, _m), members in groups.items():
            members.sort(key=lambda tid: self._last_score.get(
                tid, float("-inf")), reverse=True)
            keep = max(1, int(len(members) / self.rf))
            for tid in members[:keep]:
                self._rung_idx[tid] += 1
            for tid in members[keep:]:
                self._halted.add(tid)
            del_milestone = [tid for tid in members]
            for tid in del_milestone:
                self._paused_at.pop(tid, None)

    def resume_decision(self, trial_id: str) -> str:
        if trial_id in self._halted:
            self._halted.discard(trial_id)
            return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of other
    trials' running averages at the same time step (reference
    median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = defaultdict(list)

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        score = self._score(result)
        self._history[trial_id].append(score)
        t = result.get(self.time_attr, 0)
        if t < self.grace_period:
            return CONTINUE
        others = [sum(h) / len(h) for tid, h in self._history.items()
                  if tid != trial_id and h]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(self._history[trial_id])
        return CONTINUE if best >= median else STOP


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference pbt.py): at each perturbation interval, trials in the
    bottom quantile clone the checkpoint of a top-quantile trial and
    continue with mutated hyperparameters."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None,
                 synch: bool = False):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        # synch=True: trials PAUSE at each perturbation boundary and the
        # exploit decision happens at the barrier over the whole
        # population — deterministic under trial skew (reference pbt.py
        # `synch` flag); async mode decides from whatever results exist.
        self.synch = synch
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = defaultdict(int)
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._pending_exploit: Dict[str, Dict[str, Any]] = {}

    def register_config(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if self._rng.random() < self.resample_p or not isinstance(
                    out[key], (int, float)):
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                factor = self._rng.choice([0.8, 1.2])
                out[key] = type(out[key])(out[key] * factor) \
                    if isinstance(out[key], float) else \
                    max(1, int(out[key] * factor))
        return out

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        self._latest[trial_id] = dict(result)
        t = result.get(self.time_attr, 0)
        if t - self._last_perturb[trial_id] < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        if self.synch:
            return PAUSE  # decision deferred to the on_trials_paused barrier
        self._decide_exploits([trial_id])
        return CONTINUE

    def on_trials_paused(self, trial_ids: List[str]) -> None:
        self._decide_exploits(trial_ids)

    def _decide_exploits(self, candidates: List[str]) -> None:
        """Queue exploit directives for `candidates` in the bottom
        quantile of the current population scores."""
        scores = {tid: self._score(r) for tid, r in self._latest.items()
                  if self.metric in r}
        if len(scores) < 2:
            return
        ordered = sorted(scores, key=scores.get)
        k = max(1, int(len(ordered) * self.quantile))
        bottom, top = ordered[:k], ordered[-k:]
        for trial_id in candidates:
            if trial_id in bottom and trial_id not in top:
                src = self._rng.choice(top)
                new_cfg = self._mutate(self._configs.get(src, {}))
                self._pending_exploit[trial_id] = {"source": src,
                                                   "config": new_cfg}
                self._configs[trial_id] = new_cfg

    def exploit_directive(self, trial_id: str) -> Optional[Dict[str, Any]]:
        return self._pending_exploit.pop(trial_id, None)


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference tune/schedulers/pb2.py,
    Parker-Holder et al. 2020): PBT's exploit step, but instead of
    random mutation the new hyperparameters come from a GP-bandit fit on
    (time, hyperparams) -> per-interval reward CHANGE, maximizing UCB —
    data-efficient for small populations.

    `hyperparam_bounds`: {name: [low, high]} continuous ranges. The GP
    is a plain RBF over inputs normalized to [0,1] (the reference's
    time-varying kernel reduces to this with time as a feature), and
    the acquisition argmax is random search over the bounds — exact
    optimizers add scipy for negligible gain at population scale."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None, synch: bool = False,
                 ucb_kappa: float = 2.0, n_candidates: int = 256):
        super().__init__(time_attr=time_attr, metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={}, seed=seed,
                         quantile_fraction=quantile_fraction, synch=synch)
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds "
                             "{name: [low, high]}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        # observations: (t, {hp: v}, reward_delta) — ONE per trial per
        # perturbation interval (the reference fits on interval data),
        # windowed so the O(n^3) GP solve stays bounded over long runs
        self._pb2_obs: deque = deque(maxlen=512)
        # per-trial (t, reward) anchor at the last interval boundary
        self._pb2_anchor: Dict[str, tuple] = {}

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        if self.metric in result:
            r = self._score(result)
            t = float(result.get(self.time_attr, 0))
            anchor = self._pb2_anchor.get(trial_id)
            if anchor is None:
                self._pb2_anchor[trial_id] = (t, r)
            elif t - anchor[0] >= self.interval:
                cfg = self._configs.get(trial_id, {})
                hp = {k: float(cfg.get(k, (lo + hi) / 2))
                      for k, (lo, hi) in self.bounds.items()}
                self._pb2_obs.append((t, hp, r - anchor[1]))
                self._pb2_anchor[trial_id] = (t, r)
        return super().on_trial_result(trial_id, result)

    def exploit_directive(self, trial_id: str) -> Optional[Dict[str, Any]]:
        directive = super().exploit_directive(trial_id)
        if directive is not None:
            # the clone swaps this trial's checkpoint for the source's:
            # a delta spanning the swap would be a spurious reward jump
            # credited to the NEW config, self-reinforcing the GP fit
            self._pb2_anchor.pop(trial_id, None)
        return directive

    # PBT's exploit step calls _mutate(src_config): PB2's proposal
    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        out = dict(config)
        names = sorted(self.bounds)
        if len(self._pb2_obs) < 4:
            for k in names:  # cold start: explore uniformly
                lo, hi = self.bounds[k]
                v = self._rng.uniform(lo, hi)
                # round, don't floor: int() would bias proposals down
                # and make the upper bound unreachable
                out[k] = int(round(v)) if isinstance(config.get(k), int) \
                    else v
            return out

        t_now = max(o[0] for o in self._pb2_obs)
        tmax = t_now or 1.0

        def norm_x(t, hp):
            return [t / tmax] + [
                (hp[k] - self.bounds[k][0])
                / max(self.bounds[k][1] - self.bounds[k][0], 1e-12)
                for k in names]

        X = np.array([norm_x(t, hp) for t, hp, _ in self._pb2_obs])
        y = np.array([d for _, _, d in self._pb2_obs], np.float64)
        y_std = y.std() or 1.0
        y = (y - y.mean()) / y_std

        def rbf(a, b, length=0.3):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * length ** 2))

        K = rbf(X, X) + 1e-3 * np.eye(len(X))
        alpha = np.linalg.solve(K, y)
        rng = np.random.default_rng(self._rng.randrange(2 ** 31))
        cand_hp = [{k: rng.uniform(*self.bounds[k]) for k in names}
                   for _ in range(self.n_candidates)]
        Xc = np.array([norm_x(t_now, hp) for hp in cand_hp])
        Kc = rbf(Xc, X)
        mu = Kc @ alpha
        # predictive variance (diagonal only)
        v = np.linalg.solve(K, Kc.T)
        var = np.clip(1.0 - (Kc * v.T).sum(-1), 1e-9, None)
        best = cand_hp[int(np.argmax(mu + self.kappa * np.sqrt(var)))]
        for k in names:
            out[k] = int(round(best[k])) \
                if isinstance(config.get(k), int) else best[k]
        return out


__all__ = ["TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
           "HyperBandScheduler", "MedianStoppingRule",
           "PopulationBasedTraining", "PB2", "CONTINUE", "STOP", "PAUSE"]
