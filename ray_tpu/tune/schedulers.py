"""Trial schedulers: FIFO, ASHA (async successive halving), median
stopping, HyperBand, and Population Based Training.

API surface of the reference's python/ray/tune/schedulers/ —
`async_hyperband.py` (ASHA), `median_stopping_rule.py`, `hyperband.py`,
`pbt.py` — reduced to the decision protocol the controller consumes:
on_trial_result -> CONTINUE | STOP | PAUSE, plus PBT's exploit directive
carried on the scheduler object (the controller applies checkpoint
transfer + config mutation; see tuner.py).
"""
from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if getattr(self, "metric", None) is None and metric:
            self.metric = metric
        if mode:
            self.mode = getattr(self, "mode", None) or mode

    def on_trial_add(self, trial_id: str) -> None:
        pass

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        pass

    def exploit_directive(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """PBT hook: non-None => controller should clone src trial's
        checkpoint into trial_id with the given config."""
        return None

    def on_trials_paused(self, trial_ids: List[str]) -> None:
        """Synch-barrier hook: the controller calls this when every live
        trial has either PAUSEd or terminated. The scheduler may queue
        exploit directives; the controller then resumes all paused
        trials (reference pbt.py synch=True mode)."""


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference trial_scheduler.py)."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference schedulers/async_hyperband.py): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung is stopped
    unless it is in the top 1/reduction_factor of results recorded there."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3.0):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.max_t, self.grace_period = max_t, grace_period
        self.rf = reduction_factor
        self._rungs: Dict[int, List[float]] = defaultdict(list)
        milestones = []
        t = grace_period
        while t < max_t:
            milestones.append(int(t))
            t *= reduction_factor
        self._milestones = milestones
        self._next_milestone: Dict[str, int] = {}

    def on_trial_add(self, trial_id: str) -> None:
        self._next_milestone[trial_id] = 0

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return STOP
        idx = self._next_milestone.get(trial_id, 0)
        if idx >= len(self._milestones) or t < self._milestones[idx]:
            return CONTINUE
        milestone = self._milestones[idx]
        self._next_milestone[trial_id] = idx + 1
        score = self._score(result)
        rung = self._rungs[milestone]
        rung.append(score)
        if len(rung) < self.rf:
            return CONTINUE  # not enough evidence yet
        cutoff_rank = max(1, int(len(rung) / self.rf))
        cutoff = sorted(rung, reverse=True)[cutoff_rank - 1]
        return CONTINUE if score >= cutoff else STOP


class HyperBandScheduler(AsyncHyperBandScheduler):
    """Synchronous HyperBand collapses to ASHA under a single-authority
    async controller (reference hyperband.py vs async_hyperband.py — the
    async variant is the recommended one); kept as an alias surface."""


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of other
    trials' running averages at the same time step (reference
    median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = defaultdict(list)

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        score = self._score(result)
        self._history[trial_id].append(score)
        t = result.get(self.time_attr, 0)
        if t < self.grace_period:
            return CONTINUE
        others = [sum(h) / len(h) for tid, h in self._history.items()
                  if tid != trial_id and h]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        best = max(self._history[trial_id])
        return CONTINUE if best >= median else STOP


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference pbt.py): at each perturbation interval, trials in the
    bottom quantile clone the checkpoint of a top-quantile trial and
    continue with mutated hyperparameters."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None,
                 synch: bool = False):
        self.time_attr = time_attr
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations or {})
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        # synch=True: trials PAUSE at each perturbation boundary and the
        # exploit decision happens at the barrier over the whole
        # population — deterministic under trial skew (reference pbt.py
        # `synch` flag); async mode decides from whatever results exist.
        self.synch = synch
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = defaultdict(int)
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._configs: Dict[str, Dict[str, Any]] = {}
        self._pending_exploit: Dict[str, Dict[str, Any]] = {}

    def register_config(self, trial_id: str, config: Dict[str, Any]) -> None:
        self._configs[trial_id] = dict(config)

    def _score(self, result: Dict[str, Any]) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if self._rng.random() < self.resample_p or not isinstance(
                    out[key], (int, float)):
                if isinstance(spec, Domain):
                    out[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    out[key] = self._rng.choice(spec)
                elif callable(spec):
                    out[key] = spec()
            else:
                factor = self._rng.choice([0.8, 1.2])
                out[key] = type(out[key])(out[key] * factor) \
                    if isinstance(out[key], float) else \
                    max(1, int(out[key] * factor))
        return out

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> str:
        self._latest[trial_id] = dict(result)
        t = result.get(self.time_attr, 0)
        if t - self._last_perturb[trial_id] < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        if self.synch:
            return PAUSE  # decision deferred to the on_trials_paused barrier
        self._decide_exploits([trial_id])
        return CONTINUE

    def on_trials_paused(self, trial_ids: List[str]) -> None:
        self._decide_exploits(trial_ids)

    def _decide_exploits(self, candidates: List[str]) -> None:
        """Queue exploit directives for `candidates` in the bottom
        quantile of the current population scores."""
        scores = {tid: self._score(r) for tid, r in self._latest.items()
                  if self.metric in r}
        if len(scores) < 2:
            return
        ordered = sorted(scores, key=scores.get)
        k = max(1, int(len(ordered) * self.quantile))
        bottom, top = ordered[:k], ordered[-k:]
        for trial_id in candidates:
            if trial_id in bottom and trial_id not in top:
                src = self._rng.choice(top)
                new_cfg = self._mutate(self._configs.get(src, {}))
                self._pending_exploit[trial_id] = {"source": src,
                                                   "config": new_cfg}
                self._configs[trial_id] = new_cfg

    def exploit_directive(self, trial_id: str) -> Optional[Dict[str, Any]]:
        return self._pending_exploit.pop(trial_id, None)


__all__ = ["TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
           "HyperBandScheduler", "MedianStoppingRule",
           "PopulationBasedTraining", "CONTINUE", "STOP", "PAUSE"]
