"""Trainable protocol + the trial actor that hosts one trial.

Reference surface: python/ray/tune/trainable/trainable.py (class API —
setup/step/save_checkpoint/load_checkpoint; `train()` = one step) and
function trainables reporting through the session
(python/ray/tune/trainable/function_trainable.py). Both run inside a
`_TrialActor` — the rebuild's analog of the Tune trial actor the
TuneController manages (tune_controller.py:69) — which exposes a uniform
step/save/restore RPC surface to the controller.
"""
from __future__ import annotations

import os
import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from ..train.checkpoint import Checkpoint
from ..train.session import StopTrial, TrainContext, _set_session


class Trainable:
    """Class API (reference trainable.py:293)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict[str, Any]]:
        return None

    def load_checkpoint(self, checkpoint: Any) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def train(self) -> Dict[str, Any]:
        result = self.step()
        self.iteration += 1
        return result

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        return False


_DONE = object()


class _FunctionRunner:
    """Runs a function trainable in a thread; reports stream through a
    queue, one per controller step() (reference function_trainable.py's
    RunnerThread + inter-thread queue design)."""

    def __init__(self, fn: Callable[[Dict[str, Any]], None],
                 config: Dict[str, Any], trial_dir: str,
                 checkpoint: Optional[Checkpoint]):
        self._fn = fn
        self._config = config
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._error: Optional[BaseException] = None
        self._ctx = TrainContext(
            trial_dir=trial_dir, latest_checkpoint=checkpoint,
            _report_fn=self._on_report)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._started = False
        self._last_checkpoint: Optional[Checkpoint] = None

    def _run(self) -> None:
        _set_session(self._ctx)
        try:
            self._fn(self._config)
        except StopTrial:
            pass
        except BaseException as e:  # noqa: BLE001
            self._error = e
        finally:
            _set_session(None)
            self._q.put(_DONE)

    def _on_report(self, metrics: Dict[str, Any],
                   checkpoint: Optional[Checkpoint]) -> None:
        if checkpoint is not None:
            self._last_checkpoint = checkpoint
        self._q.put((metrics, checkpoint))

    def step(self) -> Dict[str, Any]:
        if not self._started:
            self._thread.start()
            self._started = True
        item = self._q.get()
        if item is _DONE:
            if self._error is not None:
                raise self._error
            return {"__done__": True}
        metrics, ckpt = item
        out = dict(metrics)
        if ckpt is not None:
            out["__checkpoint_path__"] = ckpt.path
        return out

    def stop(self) -> None:
        self._ctx._stop_requested = True
        # unblock the runner if it is mid-report
        try:
            self._q.get_nowait()
        except queue.Empty:
            pass


class _TrialActor:
    """Uniform trial host: wraps a class Trainable or a function trainable
    behind step/save/restore/stop (what TuneController drives)."""

    def __init__(self, trainable_bytes: bytes, config: Dict[str, Any],
                 trial_id: str, trial_dir: str,
                 restore_path: Optional[str] = None):
        from .._private import serialization

        trainable = serialization.loads(trainable_bytes)
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.iteration = 0
        os.makedirs(trial_dir, exist_ok=True)
        ckpt = Checkpoint(restore_path) if restore_path else None
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self._mode = "class"
            self._obj = trainable(config)
            if restore_path:
                self._restore_class(restore_path)
        else:
            self._mode = "function"
            self._obj = _FunctionRunner(trainable, config, trial_dir, ckpt)

    # ------------------------------------------------------------------ step

    def step(self) -> Dict[str, Any]:
        try:
            if self._mode == "class":
                result = self._obj.train()
                self.iteration = self._obj.iteration
            else:
                result = self._obj.step()
                if not result.get("__done__"):
                    self.iteration += 1
        except BaseException:  # noqa: BLE001
            return {"__error__": traceback.format_exc()}
        result = dict(result)
        result.setdefault("training_iteration", self.iteration)
        result["trial_id"] = self.trial_id
        return result

    # ------------------------------------------------------------ checkpoint

    def save(self) -> Optional[str]:
        if self._mode == "class":
            d = os.path.join(self.trial_dir,
                             f"checkpoint_{self.iteration:06d}")
            os.makedirs(d, exist_ok=True)
            data = self._obj.save_checkpoint(d)
            if data is not None:
                import pickle

                with open(os.path.join(d, "_trainable_state.pkl"),
                          "wb") as f:
                    pickle.dump({"data": data,
                                 "iteration": self.iteration}, f)
            return d
        return (self._obj._last_checkpoint.path
                if self._obj._last_checkpoint else None)

    def _restore_class(self, path: str) -> None:
        import pickle

        state_file = os.path.join(path, "_trainable_state.pkl")
        if os.path.exists(state_file):
            with open(state_file, "rb") as f:
                state = pickle.load(f)
            self._obj.load_checkpoint(state["data"])
            self._obj.iteration = state.get("iteration", 0)
            self.iteration = self._obj.iteration
        else:
            self._obj.load_checkpoint(path)

    def reset(self, new_config: Dict[str, Any],
              restore_path: Optional[str] = None) -> bool:
        """PBT exploit path: swap config (+ optionally weights) in place.
        Only class trainables support in-place reset (reference
        Trainable.reset_config)."""
        if self._mode != "class":
            return False
        if not self._obj.reset_config(new_config):
            return False
        self._obj.config = dict(new_config)
        if restore_path:
            self._restore_class(restore_path)
        return True

    def stop(self) -> None:
        if self._mode == "class":
            self._obj.cleanup()
        else:
            self._obj.stop()


__all__ = ["Trainable", "_TrialActor"]
