"""Tuner + trial controller event loop.

Reference: python/ray/tune/tuner.py (Tuner.fit) driving
tune/execution/tune_controller.py:69 — an event loop that launches trial
actors, collects their results, and applies searcher + scheduler
decisions. Single-authority rebuild: trials are `_TrialActor`s (one worker
process each, gang-scheduled through the conductor), the controller polls
outstanding step() refs with ray_tpu.wait, and experiment state is
JSON-snapshotted per iteration for cluster-crash resume
(tune/execution/experiment_state.py semantics).
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ..train.checkpoint import Checkpoint
from ..train.config import RunConfig
from ..train.trainer import Result
from . import schedulers as sched_mod
from .search import BasicVariantGenerator, Searcher
from .schedulers import (CONTINUE, PAUSE, STOP, FIFOScheduler,
                         PopulationBasedTraining, TrialScheduler)

PENDING, RUNNING, TERMINATED, ERRORED = ("PENDING", "RUNNING",
                                         "TERMINATED", "ERRORED")


@dataclass
class TuneConfig:
    """Reference tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    time_budget_s: Optional[float] = None
    seed: Optional[int] = None


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    last_result: Dict[str, Any] = field(default_factory=dict)
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    actor: Any = None
    dir: str = ""

    def metric_value(self, metric: str) -> Optional[float]:
        v = self.last_result.get(metric)
        return None if v is None else float(v)


class ResultGrid:
    """Reference tune/result_grid.py."""

    def __init__(self, results: List[Result], trials: List[Trial],
                 experiment_path: str):
        self._results = results
        self._trials = trials
        self.experiment_path = experiment_path

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or getattr(self, "_default_metric", None)
        mode = mode or getattr(self, "_default_mode", "max")
        if metric is None:
            raise ValueError("metric required (none set in TuneConfig)")
        scored = [r for r in self._results if metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        keyf = lambda r: float(r.metrics[metric])  # noqa: E731
        return (max if mode == "max" else min)(scored, key=keyf)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([r.metrics for r in self._results])


def with_resources(trainable, resources: Dict[str, float]):
    """Reference tune/tune.py with_resources: attach per-trial resources."""
    trainable._tune_resources = dict(resources)
    return trainable


def with_parameters(trainable, **kwargs):
    """Reference tune/trainable/util.py with_parameters."""
    import functools

    if isinstance(trainable, type):
        class _Wrapped(trainable):  # type: ignore[misc]
            def setup(self, config):
                super().setup({**config, **kwargs})
        _Wrapped.__name__ = trainable.__name__
        return _Wrapped
    fn = functools.partial(_call_with_params, trainable, kwargs)
    return fn


def _call_with_params(fn, params, config):
    return fn(config, **params)


class Tuner:
    """Reference tune/tuner.py."""

    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.trainable = trainable
        self.param_space = dict(param_space or {})
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored_trials: List[Dict[str, Any]] = []

    @classmethod
    def restore(cls, path: str, trainable) -> "Tuner":
        """Resume a crashed/interrupted experiment from its state snapshot
        (reference Tuner.restore / experiment_state.py)."""
        with open(os.path.join(path, "tuner_state.json")) as f:
            state = json.load(f)
        t = cls(trainable,
                tune_config=TuneConfig(**state["tune_config"]),
                run_config=RunConfig(name=state["name"],
                                     storage_path=state["storage_path"]))
        t._restored_trials = state["trials"]
        return t

    # ------------------------------------------------------------------ fit

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cfg = self.tune_config
        exp_dir = self.run_config.resolved_storage_path()
        os.makedirs(exp_dir, exist_ok=True)

        searcher = cfg.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=cfg.num_samples, seed=cfg.seed)
        searcher.set_search_properties(cfg.metric, cfg.mode,
                                       self.param_space)
        scheduler = cfg.scheduler or FIFOScheduler()
        scheduler.set_search_properties(cfg.metric, cfg.mode)

        from .._private import serialization

        trainable_bytes = serialization.dumps(self.trainable)
        resources = getattr(self.trainable, "_tune_resources", {"CPU": 1.0})

        max_concurrent = cfg.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 1)))

        trials: List[Trial] = []
        # resume: completed trials come back as results, others re-run
        rerun_configs: List[Dict[str, Any]] = []
        for tstate in self._restored_trials:
            if tstate["status"] == TERMINATED:
                t = Trial(trial_id=tstate["trial_id"],
                          config=tstate["config"], status=TERMINATED,
                          last_result=tstate["last_result"],
                          checkpoint_path=tstate.get("checkpoint_path"),
                          dir=tstate.get("dir", ""))
                trials.append(t)
            else:
                rerun_configs.append(tstate["config"])

        ref_to_trial: Dict[Any, Trial] = {}
        paused: List[Trial] = []  # synch-PBT trials awaiting the barrier
        deadline = (time.monotonic() + cfg.time_budget_s
                    if cfg.time_budget_s else None)
        next_index = len(trials)
        # restored experiments only re-run their unfinished trials; the
        # searcher's sampling stream is not persisted (reference
        # experiment_state.py restores trials, not searcher RNG state)
        exhausted = bool(self._restored_trials)
        ckpt_freq = self.run_config.checkpoint_config.checkpoint_frequency

        def launch(trial: Trial) -> None:
            actor_cls = ray_tpu.remote(_trial_actor_cls())
            trial.actor = actor_cls.options(
                num_cpus=resources.get("CPU", 1.0),
                resources={k: v for k, v in resources.items()
                           if k != "CPU"} or None).remote(
                trainable_bytes, trial.config, trial.trial_id, trial.dir,
                trial.checkpoint_path)
            trial.status = RUNNING
            scheduler.on_trial_add(trial.trial_id)
            if isinstance(scheduler, PopulationBasedTraining):
                scheduler.register_config(trial.trial_id, trial.config)
            ref = trial.actor.step.remote()
            ref_to_trial[ref] = trial

        def finalize(trial: Trial, status: str,
                     error: Optional[str] = None) -> None:
            trial.status = status
            trial.error = error
            searcher.on_trial_complete(trial.trial_id, trial.last_result,
                                       error=status == ERRORED)
            scheduler.on_trial_complete(trial.trial_id, trial.last_result)
            if trial.actor is not None:
                try:
                    ray_tpu.get(trial.actor.stop.remote(), timeout=5.0)
                except Exception:
                    pass
                try:
                    ray_tpu.kill(trial.actor)
                except Exception:
                    pass
                trial.actor = None
            self._snapshot(exp_dir, trials)

        while True:
            # launch new trials up to concurrency
            running = [t for t in trials if t.status == RUNNING]
            while len(running) < max_concurrent and not exhausted:
                if rerun_configs:
                    config = rerun_configs.pop(0)
                elif deadline and time.monotonic() > deadline:
                    break
                else:
                    config = searcher.suggest(f"trial_{next_index:05d}")
                    if config is None:
                        exhausted = True
                        break
                trial = Trial(trial_id=f"trial_{next_index:05d}",
                              config=config,
                              dir=os.path.join(exp_dir,
                                               f"trial_{next_index:05d}"))
                next_index += 1
                trials.append(trial)
                launch(trial)
                running = [t for t in trials if t.status == RUNNING]

            outstanding = list(ref_to_trial.keys())
            if not outstanding:
                if paused:
                    # synch barrier: every live trial is paused at a
                    # perturbation boundary — let the scheduler decide
                    # exploits over the whole population, then resume all
                    scheduler.on_trials_paused([t.trial_id for t in paused])
                    batch, paused = paused, []
                    for trial in batch:
                        directive = scheduler.exploit_directive(
                            trial.trial_id)
                        if directive is not None:
                            self._exploit(trial, trials, directive,
                                          trainable_bytes, resources,
                                          ref_to_trial)
                        elif scheduler.resume_decision(
                                trial.trial_id) == STOP:
                            # synchronous-HyperBand halving: the rung
                            # compared the full bracket at the barrier
                            try:
                                path = ray_tpu.get(
                                    trial.actor.save.remote(), timeout=30.0)
                                if path:
                                    trial.checkpoint_path = path
                            except Exception:
                                pass
                            finalize(trial, TERMINATED)
                        else:
                            nref = trial.actor.step.remote()
                            ref_to_trial[nref] = trial
                    continue
                break
            done, _ = ray_tpu.wait(outstanding, num_returns=1, timeout=1.0)
            if deadline and time.monotonic() > deadline:
                for ref in outstanding:
                    trial = ref_to_trial.pop(ref)
                    try:
                        result = ray_tpu.get(ref)
                        self._record(trial, result)
                    except Exception:
                        pass
                    finalize(trial, TERMINATED)
                for trial in paused:
                    finalize(trial, TERMINATED)
                paused = []
                break
            if not done:
                continue
            ref = done[0]
            trial = ref_to_trial.pop(ref)
            try:
                result = ray_tpu.get(ref)
            except Exception as e:  # actor/worker death
                trial.last_result.setdefault("training_iteration", 0)
                finalize(trial, ERRORED, error=str(e))
                continue

            if result.get("__error__"):
                finalize(trial, ERRORED, error=result["__error__"])
                continue
            if result.get("__done__"):
                finalize(trial, TERMINATED)
                continue

            self._record(trial, result)
            searcher.on_trial_result(trial.trial_id, result)
            decision = CONTINUE
            if cfg.metric and cfg.metric in result:
                decision = scheduler.on_trial_result(trial.trial_id, result)
            if self._stop_criteria_met(result):
                decision = STOP
            directive = scheduler.exploit_directive(trial.trial_id)
            if directive is not None:
                self._exploit(trial, trials, directive, trainable_bytes,
                              resources, ref_to_trial)
                continue
            if decision == STOP:
                # grab a final checkpoint for class trainables
                try:
                    path = ray_tpu.get(trial.actor.save.remote(),
                                       timeout=30.0)
                    if path:
                        trial.checkpoint_path = path
                except Exception:
                    pass
                finalize(trial, TERMINATED)
            elif decision == PAUSE:
                paused.append(trial)  # resumed at the synch barrier
            else:
                if ckpt_freq and trial.last_result.get(
                        "training_iteration", 0) % ckpt_freq == 0:
                    try:
                        path = ray_tpu.get(trial.actor.save.remote(),
                                           timeout=30.0)
                        if path:
                            trial.checkpoint_path = path
                    except Exception:
                        pass
                nref = trial.actor.step.remote()
                ref_to_trial[nref] = trial

        self._snapshot(exp_dir, trials)
        results = []
        for t in trials:
            results.append(Result(
                metrics=t.last_result,
                checkpoint=(Checkpoint(t.checkpoint_path)
                            if t.checkpoint_path else None),
                error=RuntimeError(t.error) if t.error else None,
                path=t.dir, metrics_history=t.history,
                config=dict(t.config or {})))
        grid = ResultGrid(results, trials, exp_dir)
        grid._default_metric = cfg.metric
        grid._default_mode = cfg.mode
        return grid

    # -------------------------------------------------------------- helpers

    def _record(self, trial: Trial, result: Dict[str, Any]) -> None:
        if "__checkpoint_path__" in result:
            trial.checkpoint_path = result.pop("__checkpoint_path__")
        trial.last_result = result
        trial.history.append(result)

    def _stop_criteria_met(self, result: Dict[str, Any]) -> bool:
        stop = getattr(self.run_config, "stop", None)
        if stop is None:
            return False
        if callable(stop):
            return bool(stop(result.get("trial_id", ""), result))
        for k, v in stop.items():
            if k in result and result[k] >= v:
                return True
        return False

    def _exploit(self, trial: Trial, trials: List[Trial],
                 directive: Dict[str, Any], trainable_bytes: bytes,
                 resources: Dict[str, float], ref_to_trial: Dict) -> None:
        """PBT: clone source trial's checkpoint into `trial` with the
        mutated config (reference pbt.py _exploit)."""
        import ray_tpu

        src = next((t for t in trials
                    if t.trial_id == directive["source"]), None)
        new_config = directive["config"]
        src_path = None
        if src is not None and src.actor is not None:
            try:
                src_path = ray_tpu.get(src.actor.save.remote(), timeout=60.0)
                if src_path:
                    src.checkpoint_path = src_path
            except Exception:
                src_path = src.checkpoint_path
        elif src is not None:
            src_path = src.checkpoint_path

        reset_ok = False
        if trial.actor is not None:
            try:
                reset_ok = ray_tpu.get(
                    trial.actor.reset.remote(new_config, src_path),
                    timeout=60.0)
            except Exception:
                reset_ok = False
        if not reset_ok:
            # restart the actor from the source checkpoint
            if trial.actor is not None:
                try:
                    ray_tpu.kill(trial.actor)
                except Exception:
                    pass
            actor_cls = ray_tpu.remote(_trial_actor_cls())
            trial.actor = actor_cls.options(
                num_cpus=resources.get("CPU", 1.0)).remote(
                trainable_bytes, new_config, trial.trial_id, trial.dir,
                src_path)
        trial.config = new_config
        ref = trial.actor.step.remote()
        ref_to_trial[ref] = trial

    def _snapshot(self, exp_dir: str, trials: List[Trial]) -> None:
        cfg = self.tune_config
        state = {
            "name": self.run_config.name,
            "storage_path": self.run_config.storage_path,
            "tune_config": {"metric": cfg.metric, "mode": cfg.mode,
                            "num_samples": cfg.num_samples},
            "trials": [{
                "trial_id": t.trial_id, "config": _json_config(t.config),
                "status": t.status,
                "last_result": _json_config(t.last_result),
                "checkpoint_path": t.checkpoint_path, "dir": t.dir,
            } for t in trials],
        }
        tmp = os.path.join(exp_dir, ".tuner_state.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, os.path.join(exp_dir, "tuner_state.json"))


def _trial_actor_cls():
    from .trainable import _TrialActor

    return _TrialActor


def _json_config(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out


def run(trainable, *, config: Optional[Dict] = None, num_samples: int = 1,
        metric: Optional[str] = None, mode: str = "max",
        scheduler: Optional[TrialScheduler] = None,
        search_alg: Optional[Searcher] = None,
        stop: Optional[Union[Dict, Callable]] = None,
        name: Optional[str] = None,
        storage_path: Optional[str] = None,
        max_concurrent_trials: Optional[int] = None) -> ResultGrid:
    """Legacy tune.run surface (reference python/ray/tune/tune.py)."""
    if search_alg is not None and num_samples != 1:
        raise ValueError(
            "num_samples is ignored when search_alg is given — set the "
            "searcher's own num_samples instead")
    rc = RunConfig(name=name, storage_path=storage_path)
    if stop is not None:
        rc.stop = stop  # type: ignore[attr-defined]
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples,
                               scheduler=scheduler,
                               search_alg=search_alg,
                               max_concurrent_trials=max_concurrent_trials),
        run_config=rc)
    return tuner.fit()
