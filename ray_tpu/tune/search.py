"""Search spaces + search algorithms.

API surface of the reference's python/ray/tune/search/ — sample domains
(`tune.uniform/loguniform/choice/randint/grid_search`, sample_space.py) and
the default `BasicVariantGenerator` (basic_variant.py: cartesian grid
expansion x num_samples random sampling). Plugin searchers (hyperopt/optuna
/ax/...) are external packages in the reference; here the Searcher base
class is the extension point and a native TPE-free `BasicVariantGenerator`
covers grid+random.
"""
from __future__ import annotations

import copy
import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


# ------------------------------------------------------------------ domains


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, low: float, high: float, log: bool = False,
                 q: Optional[float] = None):
        self.low, self.high, self.log, self.q = low, high, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            v = math.exp(rng.uniform(math.log(self.low),
                                     math.log(self.high)))
        else:
            v = rng.uniform(self.low, self.high)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.low, self.high)


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class SampleFrom(Domain):
    def __init__(self, fn: Callable[[Dict[str, Any]], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:  # spec-aware at resolve
        raise NotImplementedError


def uniform(low: float, high: float) -> Float:
    return Float(low, high)


def quniform(low: float, high: float, q: float) -> Float:
    return Float(low, high, q=q)


def loguniform(low: float, high: float) -> Float:
    return Float(low, high, log=True)


def qloguniform(low: float, high: float, q: float) -> Float:
    return Float(low, high, log=True, q=q)


def randint(low: int, high: int) -> Integer:
    return Integer(low, high)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[Dict[str, Any]], Any]) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    """Reference tune/search/variant_generator grid marker."""
    return {"grid_search": list(values)}


# ---------------------------------------------------------------- searchers


class Searcher:
    """Reference tune/search/searcher.py surface."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def set_search_properties(self, metric: Optional[str], mode: str,
                              config: Dict[str, Any]) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


def _split_grid(space: Dict[str, Any], prefix=()) -> List[tuple]:
    """Find (key_path, values) grid_search entries, depth-first."""
    grids = []
    for k, v in space.items():
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            grids.append((prefix + (k,), v["grid_search"]))
        elif isinstance(v, dict):
            grids.extend(_split_grid(v, prefix + (k,)))
    return grids


def _set_path(d: Dict[str, Any], path: tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


def _resolve(space: Dict[str, Any], rng: random.Random,
             resolved: Dict[str, Any]) -> Dict[str, Any]:
    """Sample every Domain leaf; SampleFrom sees the partially resolved
    config (reference sample_from(lambda spec: ...) semantics)."""
    out: Dict[str, Any] = {}
    deferred: List[tuple] = []
    for k, v in space.items():
        if isinstance(v, SampleFrom):
            deferred.append((k, v))
        elif isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = _resolve(v, rng, resolved)
        else:
            out[k] = copy.deepcopy(v)
    resolved.update(out)
    for k, v in deferred:
        out[k] = v.fn(dict(resolved))
        resolved[k] = out[k]
    return out


class BasicVariantGenerator(Searcher):
    """Grid cartesian product x num_samples random samples (reference
    tune/search/basic_variant.py)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict[str, Any]]] = None):
        super().__init__()
        self._rng = random.Random(seed)
        self._variants: Iterator[Dict[str, Any]] = iter(
            self._generate(space, num_samples,
                           list(points_to_evaluate or [])))

    def _generate(self, space, num_samples, points):
        for p in points:
            cfg = dict(copy.deepcopy(space))
            cfg.update(p)
            yield self._sample_leaves(cfg)
        grids = _split_grid(space)
        for _ in range(num_samples):
            if grids:
                for combo in itertools.product(*(vals for _, vals in grids)):
                    cfg = copy.deepcopy(space)
                    for (path, _), val in zip(grids, combo):
                        _set_path(cfg, path, val)
                    yield self._sample_leaves(cfg)
            else:
                yield self._sample_leaves(copy.deepcopy(space))

    def _sample_leaves(self, space: Dict[str, Any]) -> Dict[str, Any]:
        return _resolve(space, self._rng, {})

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            return next(self._variants)
        except StopIteration:
            return None


__all__ = [
    "Domain", "Float", "Integer", "Categorical", "SampleFrom", "Searcher",
    "BasicVariantGenerator", "uniform", "quniform", "loguniform",
    "qloguniform", "randint", "choice", "sample_from", "grid_search",
]
