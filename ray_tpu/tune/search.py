"""Search spaces + search algorithms.

API surface of the reference's python/ray/tune/search/ — sample domains
(`tune.uniform/loguniform/choice/randint/grid_search`, sample_space.py) and
the default `BasicVariantGenerator` (basic_variant.py: cartesian grid
expansion x num_samples random sampling). Plugin searchers (hyperopt/optuna
/ax/...) are external packages in the reference; here the Searcher base
class is the extension point and a native TPE-free `BasicVariantGenerator`
covers grid+random.
"""
from __future__ import annotations

import copy
import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional


# ------------------------------------------------------------------ domains


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, low: float, high: float, log: bool = False,
                 q: Optional[float] = None):
        self.low, self.high, self.log, self.q = low, high, log, q

    def from_uniform(self, u: float) -> float:
        """Quantile transform of u in [0,1) — quasi-random searchers map
        low-discrepancy points through this."""
        if self.log:
            v = math.exp(math.log(self.low)
                         + u * (math.log(self.high) - math.log(self.low)))
        else:
            v = self.low + u * (self.high - self.low)
        if self.q:
            v = round(v / self.q) * self.q
        return v

    def sample(self, rng: random.Random) -> float:
        return self.from_uniform(rng.random())


class Integer(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def from_uniform(self, u: float) -> int:
        return self.low + min(int(u * (self.high - self.low)),
                              self.high - self.low - 1)

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.low, self.high)


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def from_uniform(self, u: float) -> Any:
        return self.categories[min(int(u * len(self.categories)),
                                   len(self.categories) - 1)]

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class SampleFrom(Domain):
    def __init__(self, fn: Callable[[Dict[str, Any]], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:  # spec-aware at resolve
        raise NotImplementedError


def uniform(low: float, high: float) -> Float:
    return Float(low, high)


def quniform(low: float, high: float, q: float) -> Float:
    return Float(low, high, q=q)


def loguniform(low: float, high: float) -> Float:
    return Float(low, high, log=True)


def qloguniform(low: float, high: float, q: float) -> Float:
    return Float(low, high, log=True, q=q)


def randint(low: int, high: int) -> Integer:
    return Integer(low, high)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[Dict[str, Any]], Any]) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    """Reference tune/search/variant_generator grid marker."""
    return {"grid_search": list(values)}


# ---------------------------------------------------------------- searchers


class Searcher:
    """Reference tune/search/searcher.py surface."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric, self.mode = metric, mode

    def set_search_properties(self, metric: Optional[str], mode: str,
                              config: Dict[str, Any]) -> bool:
        """Fill properties the searcher was NOT constructed with — an
        explicit TPESearcher(mode="min") must not be flipped by the
        TuneConfig default."""
        if self.metric is None and metric:
            self.metric = metric
        if self.mode is None and mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


def _split_grid(space: Dict[str, Any], prefix=()) -> List[tuple]:
    """Find (key_path, values) grid_search entries, depth-first."""
    grids = []
    for k, v in space.items():
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            grids.append((prefix + (k,), v["grid_search"]))
        elif isinstance(v, dict):
            grids.extend(_split_grid(v, prefix + (k,)))
    return grids


def _set_path(d: Dict[str, Any], path: tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


def _resolve(space: Dict[str, Any], rng: random.Random,
             resolved: Dict[str, Any]) -> Dict[str, Any]:
    """Sample every Domain leaf; SampleFrom sees the partially resolved
    config (reference sample_from(lambda spec: ...) semantics)."""
    out: Dict[str, Any] = {}
    deferred: List[tuple] = []
    for k, v in space.items():
        if isinstance(v, SampleFrom):
            deferred.append((k, v))
        elif isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = _resolve(v, rng, resolved)
        else:
            out[k] = copy.deepcopy(v)
    resolved.update(out)
    for k, v in deferred:
        out[k] = v.fn(dict(resolved))
        resolved[k] = out[k]
    return out


def _expand_grids(space: Dict[str, Any]):
    """Yield deepcopies of `space` with every grid_search combination
    pre-set (one plain copy when there are no grids) — shared by the
    random and quasi-random generators."""
    grids = _split_grid(space)
    if not grids:
        yield copy.deepcopy(space)
        return
    for combo in itertools.product(*(vals for _, vals in grids)):
        cfg = copy.deepcopy(space)
        for (path, _), val in zip(grids, combo):
            _set_path(cfg, path, val)
        yield cfg


class BasicVariantGenerator(Searcher):
    """Grid cartesian product x num_samples random samples (reference
    tune/search/basic_variant.py)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict[str, Any]]] = None):
        super().__init__()
        self._rng = random.Random(seed)
        self._variants: Iterator[Dict[str, Any]] = iter(
            self._generate(space, num_samples,
                           list(points_to_evaluate or [])))

    def _generate(self, space, num_samples, points):
        for p in points:
            cfg = dict(copy.deepcopy(space))
            cfg.update(p)
            yield self._sample_leaves(cfg)
        for _ in range(num_samples):
            for cfg in _expand_grids(space):
                yield self._sample_leaves(cfg)

    def _sample_leaves(self, space: Dict[str, Any]) -> Dict[str, Any]:
        return _resolve(space, self._rng, {})

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            return next(self._variants)
        except StopIteration:
            return None


def _halton(index: int, base: int) -> float:
    """index-th element (1-based) of the Halton sequence in `base`."""
    f, r = 1.0, 0.0
    while index > 0:
        f /= base
        r += f * (index % base)
        index //= base
    return r


_PRIMES = (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47, 53,
           59, 61, 67, 71, 73, 79, 83, 89, 97, 101, 103, 107, 109, 113)


def _domain_paths(space: Dict[str, Any], prefix=()) -> List[tuple]:
    """Stable depth-first (key_path, Domain) enumeration — each Domain
    leaf owns one Halton dimension."""
    out = []
    for k in sorted(space, key=str):
        v = space[k]
        if isinstance(v, SampleFrom):
            continue  # resolved normally after the quasi-random leaves
        if isinstance(v, Domain):
            out.append((prefix + (k,), v))
        elif isinstance(v, dict) and set(v.keys()) != {"grid_search"}:
            out.extend(_domain_paths(v, prefix + (k,)))
    return out


class HaltonSearchGenerator(Searcher):
    """Low-discrepancy (quasi-random) search: every Domain leaf gets a
    Halton dimension (co-prime bases) mapped through its quantile, so N
    trials stratify the space far more evenly than N random draws —
    the native stand-in for the reference's plugin quasi-random
    searchers (tune/search/ zoopt/skopt-style spaces). grid_search
    entries expand cartesian like BasicVariantGenerator; sample_from
    leaves resolve normally against the partially-built config."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, skip: int = 0):
        super().__init__()
        self._rng = random.Random(seed)  # SampleFrom leaves only
        paths = _domain_paths(space)
        if len(paths) > len(_PRIMES):
            raise ValueError(
                f"HaltonSearchGenerator supports up to {len(_PRIMES)} "
                f"domain dimensions; got {len(paths)}")
        self._variants = iter(
            self._generate(space, paths, num_samples, skip))

    def _generate(self, space, paths, num_samples, skip):
        idx = skip  # Halton index 0 is all-zeros; advance before use
        for _ in range(num_samples):
            for cfg in _expand_grids(space):
                # one Halton point PER TRIAL — grid combos must not
                # share a point or continuous dims collapse to
                # num_samples distinct values across the product
                idx += 1
                for (path, dom), base in zip(paths, _PRIMES):
                    _set_path(cfg, path,
                              dom.from_uniform(_halton(idx, base)))
                # remaining SampleFrom leaves resolve normally
                yield _resolve(cfg, self._rng, {})

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        try:
            return next(self._variants)
        except StopIteration:
            return None


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (Bergstra et al. 2011) — the
    model-based half of a BOHB setup (the reference integrates
    hyperopt/BOHB as plugin searchers; this is the native
    implementation). Completed trials split into a good quantile and
    the rest; per-dimension KDEs over unit space model each group, and
    suggestions maximize the density ratio l_good/l_bad over sampled
    candidates. Pair with AsyncHyperBandScheduler for BOHB-style
    multi-fidelity search:

        tune.run(f, search_alg=TPESearcher(space, num_samples=64),
                 scheduler=tune.AsyncHyperBandScheduler(...))
    """

    def __init__(self, space: Dict[str, Any], num_samples: int = 32,
                 metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 n_initial: int = 10, gamma: float = 0.25,
                 n_ei_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(metric, mode)
        if _split_grid(space):
            raise ValueError("TPESearcher models continuous/categorical "
                             "Domains; use BasicVariantGenerator for "
                             "grid_search spaces")
        self._space = space
        self._paths = _domain_paths(space)
        if not self._paths:
            raise ValueError("TPESearcher needs at least one Domain "
                             "(tune.uniform/randint/choice) in the space")
        self._num = num_samples
        self._issued = 0
        self._n_initial = n_initial
        self._gamma = gamma
        self._n_cand = n_ei_candidates
        self._rng = random.Random(seed)
        # trial_id -> unit-space vector of the issued config
        self._pending: Dict[str, List[float]] = {}
        self._obs: List[tuple] = []  # (unit vector, score)

    # ------------------------------------------------------------ model
    def _kde_logpdf(self, u: float, centers: List[float]) -> float:
        n = len(centers)
        bw = max(0.1, 1.06 * n ** (-0.2) * 0.25)
        acc = 0.0
        for c in centers:
            acc += math.exp(-0.5 * ((u - c) / bw) ** 2)
        return math.log(acc / (n * bw) + 1e-12)

    def _propose_unit(self) -> List[float]:
        ordered = sorted(self._obs, key=lambda o: -o[1])
        k = max(1, int(len(ordered) * self._gamma))
        good = [o[0] for o in ordered[:k]]
        bad = [o[0] for o in ordered[k:]] or good
        best, best_score = None, -math.inf
        for _ in range(self._n_cand):
            # draw from the good KDE: pick a good point, jitter per dim
            base = self._rng.choice(good)
            cand = [min(max(b + self._rng.gauss(0.0, 0.15), 0.0), 1.0)
                    for b in base]
            score = sum(
                self._kde_logpdf(u, [g[i] for g in good])
                - self._kde_logpdf(u, [b[i] for b in bad])
                for i, u in enumerate(cand))
            if score > best_score:
                best, best_score = cand, score
        return best

    # --------------------------------------------------------- protocol
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._issued >= self._num:
            return None
        self._issued += 1
        if len(self._obs) < max(1, self._n_initial):
            unit = [self._rng.random() for _ in self._paths]
        else:
            unit = self._propose_unit()
        cfg = copy.deepcopy(self._space)
        for (path, dom), u in zip(self._paths, unit):
            _set_path(cfg, path, dom.from_uniform(u))
        self._pending[trial_id] = unit
        return _resolve(cfg, self._rng, {})

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        unit = self._pending.pop(trial_id, None)
        if unit is None or error or not result or \
                self.metric not in result:
            return
        v = float(result[self.metric])
        self._obs.append((unit, -v if self.mode == "min" else v))


__all__ = [
    "Domain", "Float", "Integer", "Categorical", "SampleFrom", "Searcher",
    "BasicVariantGenerator", "HaltonSearchGenerator", "TPESearcher", "uniform",
    "quniform", "loguniform", "qloguniform", "randint", "choice",
    "sample_from", "grid_search",
]
