"""External-searcher adapters — the plugin half of the reference's
tune/search/ packages (hyperopt/optuna/ax/...): a protocol that lets any
suggest/observe optimization library drive trial configs, plus a
concrete optuna integration behind an optional import.

Reference anchors: python/ray/tune/search/hyperopt/hyperopt_search.py
(:552-line adapter shape — space conversion, suggest, on_trial_complete
bookkeeping) and tune/search/optuna/optuna_search.py (ask/tell protocol).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from .search import (Categorical, Domain, Float, Integer, Searcher,
                     _domain_paths, _resolve, _set_path)

__all__ = ["wrap_searcher", "ExternalSearcher", "OptunaSearcher"]


class ExternalSearcher(Searcher):
    """Adapter: any library exposing ask/tell drives the search.

    `ask(trial_id) -> flat {name: value} | None` proposes parameters for
    the flattened domain names this adapter publishes via
    `self.param_names`; `tell(trial_id, score | None, error: bool)` feeds
    the final result back. The adapter owns everything tune-specific:
    nested-space flattening, SampleFrom resolution, metric extraction,
    and min/max normalization (tell always receives a score to MINIMIZE,
    the convention of most optimizers)."""

    def __init__(self, space: Dict[str, Any],
                 ask: Callable[[str], Optional[Dict[str, Any]]],
                 tell: Optional[Callable[[str, Optional[float], bool],
                                         None]] = None,
                 num_samples: int = 32,
                 metric: Optional[str] = None,
                 mode: Optional[str] = None):
        super().__init__(metric, mode)
        self._space = space
        self._paths = _domain_paths(space)
        self.param_names = ["/".join(p) for p, _ in self._paths]
        self._domains = {"/".join(p): d for p, d in self._paths}
        self._ask, self._tell = ask, tell
        self._budget = num_samples
        import random

        self._rng = random.Random(0)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._budget <= 0:
            return None
        flat = self._ask(trial_id)
        if flat is None:
            return None
        self._budget -= 1
        cfg = _resolve(self._space, self._rng, {})  # fills SampleFrom etc.
        for name, value in flat.items():
            _set_path(cfg, tuple(name.split("/")), value)
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        if self._tell is None:
            return
        score: Optional[float] = None
        if result is not None and self.metric in result:
            score = float(result[self.metric])
            if (self.mode or "max") == "max":
                score = -score  # externals minimize
        self._tell(trial_id, score, error)


def wrap_searcher(space: Dict[str, Any], ask, tell=None, *,
                  num_samples: int = 32, metric: Optional[str] = None,
                  mode: Optional[str] = None) -> ExternalSearcher:
    """Functional spelling of ExternalSearcher for quick plug-ins:

        searcher = wrap_searcher(space, ask=my_lib.propose,
                                 tell=my_lib.report, metric="loss",
                                 mode="min")
    """
    return ExternalSearcher(space, ask, tell, num_samples=num_samples,
                            metric=metric, mode=mode)


class OptunaSearcher(Searcher):
    """Optuna-backed search via the ask/tell API — reference
    tune/search/optuna/optuna_search.py. Requires `optuna` (optional
    dependency; importing this class without it raises ImportError with
    the install hint)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 32,
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 sampler: Any = None, seed: Optional[int] = None):
        super().__init__(metric, mode)
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearcher requires the optional 'optuna' package"
            ) from e
        self._optuna = optuna
        self._space = space
        self._paths = _domain_paths(space)
        if not self._paths:
            raise ValueError("space has no tunable Domains")
        self._distributions = {
            "/".join(p): self._to_distribution(d) for p, d in self._paths}
        self._budget = num_samples
        if sampler is None and seed is not None:
            sampler = optuna.samplers.TPESampler(seed=seed)
        optuna.logging.set_verbosity(optuna.logging.WARNING)
        # direction fixed to minimize; mode normalization happens in tell
        self._study = optuna.create_study(sampler=sampler,
                                          direction="minimize")
        self._trials: Dict[str, Any] = {}
        import random

        self._rng = random.Random(seed or 0)

    def _to_distribution(self, dom: Domain):
        optuna = self._optuna
        if isinstance(dom, Float):
            return optuna.distributions.FloatDistribution(
                dom.low, dom.high, log=dom.log,
                step=dom.q if (dom.q and not dom.log) else None)
        if isinstance(dom, Integer):
            return optuna.distributions.IntDistribution(
                dom.low, dom.high - 1)  # ours is randrange-style
        if isinstance(dom, Categorical):
            return optuna.distributions.CategoricalDistribution(
                dom.categories)
        raise TypeError(f"unsupported domain {type(dom).__name__}")

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._budget <= 0:
            return None
        self._budget -= 1
        trial = self._study.ask(self._distributions)
        self._trials[trial_id] = trial
        cfg = _resolve(self._space, self._rng, {})
        for name, value in trial.params.items():
            _set_path(cfg, tuple(name.split("/")), value)
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        trial = self._trials.pop(trial_id, None)
        if trial is None:
            return
        state = self._optuna.trial.TrialState.COMPLETE
        value = None
        if error or result is None or self.metric not in result:
            state = self._optuna.trial.TrialState.FAIL
        else:
            value = float(result[self.metric])
            if (self.mode or "max") == "max":
                value = -value
        self._study.tell(trial, value, state=state)
