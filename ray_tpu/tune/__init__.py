"""ray_tpu.tune: hyperparameter search over trial actors.

Capability surface of the reference's Ray Tune (python/ray/tune/ —
SURVEY.md §2.4): Tuner.fit driving a trial-actor event loop, grid/random
search with composable sample domains, ASHA / HyperBand / median-stopping
/ PBT schedulers, function + class trainables reporting through the
shared train session, experiment snapshots with Tuner.restore.

TPU-first deltas: trials that train on-device use the driver-held mesh
(one trial per host-process is the CPU-search story; chip-level search
runs trials sequentially against the mesh the driver owns), and trial
state is snapshotted through the same checkpoint layer as ray_tpu.train.
"""
from ..train.session import get_checkpoint, get_context, report  # noqa: F401
from .search import (  # noqa: F401
    BasicVariantGenerator,
    HaltonSearchGenerator,
    TPESearcher,
    Searcher,
    choice,
    grid_search,
    loguniform,
    qloguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from .external import (  # noqa: F401
    ExternalSearcher,
    OptunaSearcher,
    wrap_searcher,
)
from .schedulers import (  # noqa: F401
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from .trainable import Trainable  # noqa: F401
from .tuner import (  # noqa: F401
    ResultGrid,
    TuneConfig,
    Tuner,
    run,
    with_parameters,
    with_resources,
)

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "run", "Trainable",
    "with_parameters", "with_resources", "report", "get_checkpoint",
    "get_context", "uniform", "quniform", "loguniform", "qloguniform",
    "randint", "choice", "sample_from", "grid_search", "Searcher",
    "BasicVariantGenerator", "HaltonSearchGenerator", "TPESearcher",
    "ExternalSearcher", "OptunaSearcher", "wrap_searcher",
    "TrialScheduler", "FIFOScheduler",
    "AsyncHyperBandScheduler", "ASHAScheduler", "HyperBandScheduler",
    "MedianStoppingRule", "PopulationBasedTraining", "PB2",
]
