"""User-facing exceptions.

Mirrors the surface of the reference's python/ray/exceptions.py (RayTaskError,
RayActorError, WorkerCrashedError, GetTimeoutError, ObjectLostError, ...).
"""
from __future__ import annotations


class RayTpuError(Exception):
    """Base class for framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at `get` with the remote
    traceback attached (reference: RayTaskError.as_instanceof_cause)."""

    def __init__(self, cause: BaseException, remote_traceback: str = "",
                 task_name: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_name = task_name
        super().__init__(
            f"task {task_name or '<unknown>'} failed: "
            f"{type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{remote_traceback}")


class ActorError(RayTpuError):
    """The actor died before or during this call."""

    def __init__(self, actor_id: str = "", cause: str = ""):
        self.actor_id = actor_id
        super().__init__(f"actor {actor_id[:12]}… died: {cause}")


class ActorDiedError(ActorError):
    pass


class ActorUnavailableError(ActorError):
    """Actor is restarting; the call may be retried."""


class WorkerCrashedError(RayTpuError):
    """The worker executing the task died (e.g. OOM-killed)."""


class OutOfMemoryError(WorkerCrashedError):
    """The memory monitor killed the worker to protect the node
    (reference: OOM-killed task errors, memory_monitor.h). Subclasses
    WorkerCrashedError so retry semantics match any worker death; the
    final error names the cause with usage numbers."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    def __init__(self, object_id: str = "", msg: str = ""):
        self.object_id = object_id
        super().__init__(f"object {object_id[:12]}… lost{': ' + msg if msg else ''}")


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class SchedulingError(RayTpuError):
    """Placement can never be satisfied (e.g. hard NodeAffinity to a dead
    or too-small node) — fails the task instead of waiting forever."""


class PlacementGroupSchedulingError(RayTpuError):
    pass
