"""RLModule core: JAX policy/value networks + action distributions + GAE
and V-trace — the compute kernel layer of the RL stack.

Reference surface: rllib/core/rl_module/ (RLModule forward_* methods),
rllib/models/ (distributions), rllib/evaluation/postprocessing.py (GAE),
rllib/algorithms/impala/vtrace_torch.py (V-trace). Reimplemented as pure
jittable functions — losses/advantages compile into the learner's SPMD
update instead of running eagerly per batch on the driver.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------------- MLPs


def mlp_init(key: jax.Array, sizes: List[int]) -> List[Dict[str, jax.Array]]:
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        scale = np.sqrt(2.0 / sizes[i])
        params.append({
            "w": jax.random.normal(sub, (sizes[i], sizes[i + 1])) * scale,
            "b": jnp.zeros((sizes[i + 1],)),
        })
    return params


def mlp_apply(params: List[Dict[str, jax.Array]], x: jax.Array,
              final_scale: float = 1.0) -> jax.Array:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x * final_scale


def policy_init(key: jax.Array, obs_dim: int, act_dim: int,
                hidden: Tuple[int, ...] = (64, 64),
                continuous: bool = False) -> Dict[str, Any]:
    """pi + vf torso params (separate networks, reference MLP default).
    Continuous policies get a state-independent log_std."""
    k1, k2 = jax.random.split(key)
    params = {
        "pi": mlp_init(k1, [obs_dim, *hidden, act_dim]),
        "vf": mlp_init(k2, [obs_dim, *hidden, 1]),
    }
    if continuous:
        params["log_std"] = jnp.zeros((act_dim,))
    return params


def policy_logits(params: Dict[str, Any], obs: jax.Array) -> jax.Array:
    return mlp_apply(params["pi"], obs, final_scale=0.01)


def value(params: Dict[str, Any], obs: jax.Array) -> jax.Array:
    return mlp_apply(params["vf"], obs)[..., 0]


# ---------------------------------------------------------- distributions


def categorical_sample(key: jax.Array, logits: jax.Array) -> jax.Array:
    return jax.random.categorical(key, logits, axis=-1)


def categorical_logp(logits: jax.Array, actions: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, actions[..., None],
                               axis=-1)[..., 0]


def categorical_entropy(logits: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def gaussian_sample(key: jax.Array, mean: jax.Array,
                    log_std: jax.Array) -> jax.Array:
    return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)


def gaussian_logp(mean: jax.Array, log_std: jax.Array,
                  actions: jax.Array) -> jax.Array:
    var = jnp.exp(2 * log_std)
    return jnp.sum(-0.5 * ((actions - mean) ** 2 / var
                           + 2 * log_std + jnp.log(2 * jnp.pi)), axis=-1)


def gaussian_entropy(log_std: jax.Array) -> jax.Array:
    return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e))


# ------------------------------------------------------------------- GAE


def compute_gae(rewards: jax.Array, values: jax.Array, dones: jax.Array,
                gamma: float = 0.99, lam: float = 0.95
                ) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation (reference
    evaluation/postprocessing.py compute_advantages), as a lax.scan over
    time. rewards/dones: [T, N]; values: [T+1, N] (bootstrapped).
    Returns (advantages [T, N], value_targets [T, N])."""
    not_done = 1.0 - dones.astype(values.dtype)

    def step(carry, t):
        gae = carry
        delta = rewards[t] + gamma * values[t + 1] * not_done[t] - values[t]
        gae = delta + gamma * lam * not_done[t] * gae
        return gae, gae

    T = rewards.shape[0]
    _, adv_rev = jax.lax.scan(step, jnp.zeros_like(values[0]),
                              jnp.arange(T - 1, -1, -1))
    advantages = adv_rev[::-1]
    return advantages, advantages + values[:-1]


# ---------------------------------------------------------------- V-trace


def vtrace(behavior_logp: jax.Array, target_logp: jax.Array,
           rewards: jax.Array, values: jax.Array, dones: jax.Array,
           gamma: float = 0.99, clip_rho: float = 1.0,
           clip_c: float = 1.0) -> Tuple[jax.Array, jax.Array]:
    """IMPALA V-trace off-policy correction (Espeholt et al. 2018;
    reference impala/vtrace_torch.py). Shapes as compute_gae; logp [T, N].
    Returns (pg_advantages [T, N], vs targets [T, N])."""
    not_done = 1.0 - dones.astype(values.dtype)
    rhos = jnp.exp(target_logp - behavior_logp)
    rho_bar = jnp.minimum(rhos, clip_rho)
    c_bar = jnp.minimum(rhos, clip_c)

    def step(carry, t):
        acc = carry
        delta = rho_bar[t] * (
            rewards[t] + gamma * values[t + 1] * not_done[t] - values[t])
        acc = delta + gamma * not_done[t] * c_bar[t] * acc
        return acc, acc

    T = rewards.shape[0]
    _, vs_minus_v_rev = jax.lax.scan(step, jnp.zeros_like(values[0]),
                                     jnp.arange(T - 1, -1, -1))
    vs_minus_v = vs_minus_v_rev[::-1]
    vs = vs_minus_v + values[:-1]
    vs_next = jnp.concatenate([vs[1:], values[-1:]], axis=0)
    pg_adv = rho_bar * (rewards + gamma * vs_next * not_done - values[:-1])
    return pg_adv, vs


__all__ = ["mlp_init", "mlp_apply", "policy_init", "policy_logits", "value",
           "categorical_sample", "categorical_logp", "categorical_entropy",
           "gaussian_sample", "gaussian_logp", "gaussian_entropy",
           "compute_gae", "vtrace"]
