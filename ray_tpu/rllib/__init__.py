"""ray_tpu.rllib: reinforcement learning on TPU meshes.

Capability surface of the reference's RLlib (rllib/ — SURVEY.md §2.4):
AlgorithmConfig builder -> Algorithm (a Tune Trainable), EnvRunner actors
stepping vector envs, and learners updating policies from rollouts. The
reference's torch-DDP learner path (core/learner/torch/torch_learner.py:
265,384-395 NCCL allreduce) becomes a single jitted update — GAE/V-trace,
minibatch SGD and gradient sync compile into one XLA program that runs
SPMD over a dp mesh axis on TPU.

Algorithms: PPO (sync on-policy, ppo.py), IMPALA (async off-policy with
V-trace, impala.py), APPO (IMPALA's async loop + clipped surrogate +
target network, appo.py — the reference's v4-32 north-star variant), and
DQN (replay buffer + double-Q + target sync, dqn.py), and SAC (twin
soft-Q + squashed gaussian + auto-alpha for continuous control, sac.py)
— covering the reference's sync/async/off-policy execution plans.
Offline RL: shard recording, OfflineData, behavior cloning
(offline.py), MARWIL advantage-weighted imitation (marwil.py), and
CQL conservative Q-learning (cql.py). Model-based: DreamerV3 — RSSM
world model + imagination actor-critic in one jitted update
(dreamerv3.py). Multi-agent:
MultiAgentEnvRunner collects per-policy batches via policy_mapping_fn
(multi_agent.py). Native vectorized CartPole/Pendulum remove the
gymnasium dependency from tests; any gymnasium env id works via the
adapter.
"""
from .algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from .env import (  # noqa: F401
    CartPoleVectorEnv,
    GymnasiumVectorEnv,
    PendulumVectorEnv,
    VectorEnv,
    make_env,
    register_env,
)
from .env_runner import EnvRunner, make_remote_runners  # noqa: F401
from .appo import APPO, APPOConfig  # noqa: F401
from .dqn import DQN, DQNConfig, QEnvRunner, ReplayBuffer  # noqa: F401
from .dreamerv3 import DreamerV3, DreamerV3Config  # noqa: F401
from .impala import IMPALA, IMPALAConfig  # noqa: F401
from .multi_agent import (  # noqa: F401
    MultiAgentCartPole,
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentVectorEnv,
    make_multi_agent_env,
    register_multi_agent_env,
)
from .connectors import (  # noqa: F401
    ClipActions,
    ClipObservations,
    Connector,
    ConnectorPipeline,
    NormalizeObservations,
    ScaleActions,
)
from .cql import CQL, CQLConfig  # noqa: F401
from .marwil import MARWIL, MARWILConfig  # noqa: F401
from .offline import BC, BCConfig, OfflineData, record_batches  # noqa: F401
from .ppo import PPO, PPOConfig  # noqa: F401
from .sac import SAC, SACConfig  # noqa: F401

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "IMPALA",
    "IMPALAConfig", "APPO", "APPOConfig", "DQN", "DQNConfig",
    "QEnvRunner", "ReplayBuffer", "EnvRunner", "make_remote_runners",
    "VectorEnv", "CartPoleVectorEnv", "PendulumVectorEnv",
    "GymnasiumVectorEnv", "register_env", "make_env",
    "MultiAgentVectorEnv", "MultiAgentCartPole", "MultiAgentEnvRunner",
    "MultiAgentPPO", "make_multi_agent_env", "register_multi_agent_env",
    "BC", "BCConfig", "OfflineData", "record_batches", "SAC", "SACConfig",
    "MARWIL", "MARWILConfig", "CQL", "CQLConfig",
    "DreamerV3", "DreamerV3Config",
    "Connector", "ConnectorPipeline", "NormalizeObservations",
    "ClipObservations", "ClipActions", "ScaleActions",
]
