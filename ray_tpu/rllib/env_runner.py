"""EnvRunner: collects rollouts from a vector env with a jitted policy.

Reference: rllib/env/single_agent_env_runner.py — an actor stepping
gymnasium vector envs with RLModule inference. Here inference is a jitted
CPU policy forward (runners live on host workers; JAX_PLATFORMS=cpu), and
the same class runs in-process for num_env_runners=0 (reference "local
EnvRunner" mode) or as a ray_tpu actor for the distributed fleet.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .env import make_env


def build_act_fn(continuous: bool):
    """The jitted sampling forward shared by single- and multi-agent
    runners: (params, obs, key) -> (actions, logp)."""
    import jax

    from . import core

    @jax.jit
    def act(params, obs, key):
        if continuous:
            mean = core.policy_logits(params, obs)
            a = core.gaussian_sample(key, mean, params["log_std"])
            logp = core.gaussian_logp(mean, params["log_std"], a)
        else:
            logits = core.policy_logits(params, obs)
            a = core.categorical_sample(key, logits)
            logp = core.categorical_logp(logits, a)
        return a, logp

    return act


class EnvRunner:
    def __init__(self, env: Any, *, num_envs: int = 1,
                 rollout_fragment_length: int = 128, seed: int = 0,
                 env_config: Optional[Dict] = None,
                 env_to_module: Optional[Callable] = None,
                 module_to_env: Optional[Callable] = None):
        self.env = make_env(env, num_envs, env_config, seed=seed)
        self.T = rollout_fragment_length
        self.continuous = self.env.num_actions < 0
        # connector pipelines (reference rllib/connectors/): accept a
        # Connector instance or a zero-arg factory (remote runners build
        # their own stateful instances from the factory)
        from .connectors import resolve_connector

        self._env_to_module = resolve_connector(env_to_module)
        self._module_to_env = resolve_connector(module_to_env)
        self._rng_key = None
        self._seed = seed
        self._obs = self.env.reset(seed=seed)
        self._ep_returns = np.zeros(self.env.num_envs, np.float64)
        self._ep_lens = np.zeros(self.env.num_envs, np.int64)
        self._completed: List[float] = []
        self._completed_lens: List[int] = []
        self._act_fn = None

    # --------------------------------------------------------- connectors
    def get_connector_states(self):
        return {
            "env_to_module": self._env_to_module.get_state()
            if self._env_to_module is not None else None,
            "module_to_env": self._module_to_env.get_state()
            if self._module_to_env is not None else None,
        }

    def set_connector_states(self, states) -> None:
        if states.get("env_to_module") is not None \
                and self._env_to_module is not None:
            self._env_to_module.set_state(states["env_to_module"])
        if states.get("module_to_env") is not None \
                and self._module_to_env is not None:
            self._module_to_env.set_state(states["module_to_env"])

    def pop_connector_deltas(self):
        """Per-sync NEW statistics only (see Connector.pop_delta) — the
        driver merges these into the global state and broadcasts it."""
        return {
            "env_to_module": self._env_to_module.pop_delta()
            if self._env_to_module is not None else None,
            "module_to_env": self._module_to_env.pop_delta()
            if self._module_to_env is not None else None,
        }

    # ------------------------------------------------------------- policy

    def _build_act(self):
        return build_act_fn(self.continuous)

    def sample(self, params: Any) -> Dict[str, Any]:
        """One rollout fragment: T steps x num_envs. Returns numpy batch
        {obs [T+1,N,D], actions, logp, rewards, dones [T,N]} + episode
        stats of episodes completed during the fragment."""
        import jax

        if self._act_fn is None:
            self._act_fn = self._build_act()
            self._rng_key = jax.random.PRNGKey(self._seed)
        n, d = self.env.num_envs, self.env.observation_dim
        obs_buf = np.empty((self.T + 1, n, d), np.float32)
        act_dtype = np.float32 if self.continuous else np.int32
        act_shape = (self.T, n, self.env.act_dim) if self.continuous \
            else (self.T, n)
        act_buf = np.empty(act_shape, act_dtype)
        logp_buf = np.empty((self.T, n), np.float32)
        rew_buf = np.empty((self.T, n), np.float32)
        done_buf = np.empty((self.T, n), np.bool_)

        self._completed.clear()
        self._completed_lens.clear()
        obs = self._obs
        for t in range(self.T):
            self._rng_key, sub = jax.random.split(self._rng_key)
            # the batch records what the module SAW (transformed obs)
            # and what it OUTPUT (raw action, consistent with logp);
            # only the env receives the transformed action
            mobs = self._env_to_module(obs) \
                if self._env_to_module is not None else obs
            a, logp = self._act_fn(params, mobs, sub)
            a = np.asarray(a)
            obs_buf[t] = mobs
            act_buf[t] = a.astype(act_dtype)
            logp_buf[t] = np.asarray(logp)
            env_a = self._module_to_env(a) \
                if self._module_to_env is not None else a
            obs, rew, done = self.env.step(env_a)
            rew_buf[t] = rew
            done_buf[t] = done
            self._ep_returns += rew
            self._ep_lens += 1
            if done.any():
                for i in np.flatnonzero(done):
                    self._completed.append(float(self._ep_returns[i]))
                    self._completed_lens.append(int(self._ep_lens[i]))
                self._ep_returns[done] = 0.0
                self._ep_lens[done] = 0
        obs_buf[self.T] = self._env_to_module(obs, update=False) \
            if self._env_to_module is not None else obs
        self._obs = obs
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "rewards": rew_buf, "dones": done_buf,
            "episode_returns": list(self._completed),
            "episode_lens": list(self._completed_lens),
        }

    def env_spec(self) -> Dict[str, int]:
        return {"obs_dim": self.env.observation_dim,
                "num_actions": self.env.num_actions,
                "act_dim": self.env.act_dim,
                "num_envs": self.env.num_envs}


def make_remote_runners(env: Any, *, num_runners: int, num_envs: int,
                        rollout_fragment_length: int,
                        env_config: Optional[Dict] = None,
                        seed: int = 0, runner_cls: type = None,
                        env_to_module: Optional[Callable] = None,
                        module_to_env: Optional[Callable] = None
                        ) -> List[Any]:
    """Spawn EnvRunner actors (reference EnvRunnerGroup /
    rollout worker set). Connector args should be zero-arg FACTORIES so
    every runner owns its stateful pipeline instance."""
    import ray_tpu

    cls = ray_tpu.remote(runner_cls or EnvRunner)
    return [cls.options(num_cpus=1.0).remote(
        env, num_envs=num_envs,
        rollout_fragment_length=rollout_fragment_length,
        seed=seed + 1000 * (i + 1), env_config=env_config,
        env_to_module=env_to_module, module_to_env=module_to_env)
        for i in range(num_runners)]


__all__ = ["EnvRunner", "build_act_fn", "make_remote_runners"]
