"""EnvRunner: collects rollouts from a vector env with a jitted policy.

Reference: rllib/env/single_agent_env_runner.py — an actor stepping
gymnasium vector envs with RLModule inference. Here inference is a jitted
CPU policy forward (runners live on host workers; JAX_PLATFORMS=cpu), and
the same class runs in-process for num_env_runners=0 (reference "local
EnvRunner" mode) or as a ray_tpu actor for the distributed fleet.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .env import make_env


def build_act_fn(continuous: bool):
    """The jitted sampling forward shared by single- and multi-agent
    runners: (params, obs, key) -> (actions, logp)."""
    import jax

    from . import core

    @jax.jit
    def act(params, obs, key):
        if continuous:
            mean = core.policy_logits(params, obs)
            a = core.gaussian_sample(key, mean, params["log_std"])
            logp = core.gaussian_logp(mean, params["log_std"], a)
        else:
            logits = core.policy_logits(params, obs)
            a = core.categorical_sample(key, logits)
            logp = core.categorical_logp(logits, a)
        return a, logp

    return act


class EnvRunner:
    def __init__(self, env: Any, *, num_envs: int = 1,
                 rollout_fragment_length: int = 128, seed: int = 0,
                 env_config: Optional[Dict] = None):
        self.env = make_env(env, num_envs, env_config, seed=seed)
        self.T = rollout_fragment_length
        self.continuous = self.env.num_actions < 0
        self._rng_key = None
        self._seed = seed
        self._obs = self.env.reset(seed=seed)
        self._ep_returns = np.zeros(self.env.num_envs, np.float64)
        self._ep_lens = np.zeros(self.env.num_envs, np.int64)
        self._completed: List[float] = []
        self._completed_lens: List[int] = []
        self._act_fn = None

    # ------------------------------------------------------------- policy

    def _build_act(self):
        return build_act_fn(self.continuous)

    def sample(self, params: Any) -> Dict[str, Any]:
        """One rollout fragment: T steps x num_envs. Returns numpy batch
        {obs [T+1,N,D], actions, logp, rewards, dones [T,N]} + episode
        stats of episodes completed during the fragment."""
        import jax

        if self._act_fn is None:
            self._act_fn = self._build_act()
            self._rng_key = jax.random.PRNGKey(self._seed)
        n, d = self.env.num_envs, self.env.observation_dim
        obs_buf = np.empty((self.T + 1, n, d), np.float32)
        act_dtype = np.float32 if self.continuous else np.int32
        act_shape = (self.T, n, self.env.act_dim) if self.continuous \
            else (self.T, n)
        act_buf = np.empty(act_shape, act_dtype)
        logp_buf = np.empty((self.T, n), np.float32)
        rew_buf = np.empty((self.T, n), np.float32)
        done_buf = np.empty((self.T, n), np.bool_)

        self._completed.clear()
        self._completed_lens.clear()
        obs = self._obs
        for t in range(self.T):
            self._rng_key, sub = jax.random.split(self._rng_key)
            a, logp = self._act_fn(params, obs, sub)
            a = np.asarray(a)
            obs_buf[t] = obs
            act_buf[t] = a.astype(act_dtype)
            logp_buf[t] = np.asarray(logp)
            obs, rew, done = self.env.step(a)
            rew_buf[t] = rew
            done_buf[t] = done
            self._ep_returns += rew
            self._ep_lens += 1
            if done.any():
                for i in np.flatnonzero(done):
                    self._completed.append(float(self._ep_returns[i]))
                    self._completed_lens.append(int(self._ep_lens[i]))
                self._ep_returns[done] = 0.0
                self._ep_lens[done] = 0
        obs_buf[self.T] = obs
        self._obs = obs
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "rewards": rew_buf, "dones": done_buf,
            "episode_returns": list(self._completed),
            "episode_lens": list(self._completed_lens),
        }

    def env_spec(self) -> Dict[str, int]:
        return {"obs_dim": self.env.observation_dim,
                "num_actions": self.env.num_actions,
                "act_dim": self.env.act_dim,
                "num_envs": self.env.num_envs}


def make_remote_runners(env: Any, *, num_runners: int, num_envs: int,
                        rollout_fragment_length: int,
                        env_config: Optional[Dict] = None,
                        seed: int = 0, runner_cls: type = None) -> List[Any]:
    """Spawn EnvRunner actors (reference EnvRunnerGroup /
    rollout worker set)."""
    import ray_tpu

    cls = ray_tpu.remote(runner_cls or EnvRunner)
    return [cls.options(num_cpus=1.0).remote(
        env, num_envs=num_envs,
        rollout_fragment_length=rollout_fragment_length,
        seed=seed + 1000 * (i + 1), env_config=env_config)
        for i in range(num_runners)]


__all__ = ["EnvRunner", "build_act_fn", "make_remote_runners"]
