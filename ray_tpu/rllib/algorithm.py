"""Algorithm base + AlgorithmConfig builder.

Reference: rllib/algorithms/algorithm.py (Algorithm extends Tune's
Trainable; step() -> training_step()) and algorithm_config.py (the
builder: .environment().env_runners().training().build()). The rebuild
keeps the builder surface and the Trainable integration (so
tune.Tuner(PPO...) works), while the learner update is a single jitted
SPMD function instead of a DDP-wrapped torch module
(torch_learner.py:265's NCCL path -> XLA collectives on the mesh).
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional, Tuple, Type

import numpy as np

from ..tune.trainable import Trainable
from .env import make_env
from .env_runner import EnvRunner, make_remote_runners


class AlgorithmConfig:
    """Builder (reference algorithm_config.py)."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env: Any = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 1
        self.rollout_fragment_length = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.seed = 0
        self.hidden = (64, 64)
        self.train_extra: Dict[str, Any] = {}

    # builder steps -------------------------------------------------------

    def environment(self, env: Any = None, *,
                    env_config: Optional[Dict] = None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    env_to_module_connector: Optional[Any] = None,
                    module_to_env_connector: Optional[Any] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        # zero-arg factories building connector pipelines per runner
        # (reference ConnectorV2 env_to_module/module_to_env hooks)
        if env_to_module_connector is not None:
            self.train_extra["env_to_module_connector"] = \
                env_to_module_connector
        if module_to_env_connector is not None:
            self.train_extra["module_to_env_connector"] = \
                module_to_env_connector
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 model: Optional[Dict] = None,
                 **extra) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if model and "fcnet_hiddens" in model:
            self.hidden = tuple(model["fcnet_hiddens"])
        self.train_extra.update(extra)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "env": self.env, "env_config": self.env_config,
            "num_env_runners": self.num_env_runners,
            "num_envs_per_env_runner": self.num_envs_per_env_runner,
            "rollout_fragment_length": self.rollout_fragment_length,
            "lr": self.lr, "gamma": self.gamma, "seed": self.seed,
            "hidden": self.hidden,
        }
        d.update(self.train_extra)
        return d

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("no algo_class bound to this config")
        return self.algo_class(self.to_dict())

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)


class Algorithm(Trainable):
    """Trainable whose step() is one training iteration (reference
    algorithm.py:789 step -> :1489 training_step)."""

    _default_config: Dict[str, Any] = {}
    # value-based algorithms sample with their own policy (e.g. DQN's
    # epsilon-greedy Q-net) — override to swap the collection class
    _runner_cls: Type[EnvRunner] = EnvRunner

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(cls)
        for k, v in cls._default_config.items():
            setattr(cfg, k, v) if hasattr(cfg, k) \
                else cfg.train_extra.__setitem__(k, v)
        return cfg

    # ------------------------------------------------------------- setup

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = dict(self._default_config)
        cfg.update(config)
        self.cfg = cfg
        if cfg.get("env") is None:
            raise ValueError("config['env'] is required")
        probe = make_env(cfg["env"], 1, cfg.get("env_config"),
                         seed=cfg.get("seed", 0))
        self.obs_dim = probe.observation_dim
        self.num_actions = probe.num_actions
        self.act_dim = probe.act_dim
        self.continuous = probe.num_actions < 0

        n_runners = cfg.get("num_env_runners", 0)
        e2m = cfg.get("env_to_module_connector")
        m2e = cfg.get("module_to_env_connector")
        # driver-side template instances: define merge semantics for
        # fleet stat sync and the checkpoint state shape
        from .connectors import resolve_connector

        self._e2m_template = resolve_connector(e2m)
        self._m2e_template = resolve_connector(m2e)
        self._has_connectors = e2m is not None or m2e is not None
        self._connector_states: Optional[Dict[str, Any]] = None
        if n_runners > 0:
            self.runners = make_remote_runners(
                cfg["env"], num_runners=n_runners,
                num_envs=cfg.get("num_envs_per_env_runner", 1),
                rollout_fragment_length=cfg.get("rollout_fragment_length",
                                                128),
                env_config=cfg.get("env_config"),
                seed=cfg.get("seed", 0), runner_cls=self._runner_cls,
                env_to_module=e2m, module_to_env=m2e)
            self.local_runner = None
        else:
            self.runners = []
            self.local_runner = self._runner_cls(
                cfg["env"], num_envs=cfg.get("num_envs_per_env_runner", 1),
                rollout_fragment_length=cfg.get("rollout_fragment_length",
                                                128),
                seed=cfg.get("seed", 0), env_config=cfg.get("env_config"),
                env_to_module=e2m, module_to_env=m2e)
        self._episode_returns: collections.deque = collections.deque(
            maxlen=100)
        self._episode_lens: collections.deque = collections.deque(maxlen=100)
        self._env_steps_lifetime = 0
        self._build_learner()

    def _build_learner(self) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        result = self.training_step()
        result.setdefault("episode_return_mean",
                          float(np.mean(self._episode_returns))
                          if self._episode_returns else float("nan"))
        result.setdefault("episode_len_mean",
                          float(np.mean(self._episode_lens))
                          if self._episode_lens else float("nan"))
        result.setdefault("num_env_steps_sampled_lifetime",
                          self._env_steps_lifetime)
        return result

    # ----------------------------------------------------------- sampling

    def _sample_params(self):
        """Params handed to EnvRunners — variants whose runner wants a
        different layout (SAC/CQL's {"pi", "scale"}) override this."""
        return self.params

    def _host_params(self):
        import jax

        return jax.device_get(self._sample_params())

    def _collect_batches(self) -> List[Dict[str, Any]]:
        """Synchronous fan-out (reference rollout_ops.py
        synchronous_parallel_sample)."""
        if self.local_runner is not None:
            batches = [self.local_runner.sample(self._sample_params())]
        else:
            import ray_tpu

            p = self._host_params()
            batches = ray_tpu.get(
                [r.sample.remote(p) for r in self.runners])
        for b in batches:
            self._episode_returns.extend(b["episode_returns"])
            self._episode_lens.extend(b["episode_lens"])
            self._env_steps_lifetime += int(np.prod(b["rewards"].shape))
        if self.runners and self._has_connectors:
            self._sync_connectors()
        return batches

    def _merge_connector_state(self, template, states):
        from .connectors import ConnectorPipeline

        if template is None or not states:
            return None
        if isinstance(template, ConnectorPipeline):
            return template.merge_pipeline_states(states)
        return type(template).merge_states(states)

    def _sync_connectors(self) -> None:
        """Merge each remote runner's NEW connector statistics (deltas
        since the last sync) into the global state and broadcast it —
        one policy must train on observations scaled by ONE statistic.
        Deltas, not absolute states: re-merging absolutes would
        double-count the shared broadcast history every round
        (reference: mean-std filter sync pulls per-runner buffers and
        clears them)."""
        import ray_tpu

        deltas = ray_tpu.get(
            [r.pop_connector_deltas.remote() for r in self.runners])
        prev = self._connector_states or {}
        merged = {}
        for key, tmpl in (("env_to_module", self._e2m_template),
                          ("module_to_env", self._m2e_template)):
            sts = [d[key] for d in deltas if d.get(key)]
            if prev.get(key):
                sts = [prev[key]] + sts
            merged[key] = self._merge_connector_state(tmpl, sts)
        ray_tpu.get([r.set_connector_states.remote(merged)
                     for r in self.runners])
        self._connector_states = merged

    @staticmethod
    def _concat_batches(batches: List[Dict[str, Any]]) -> Dict[str, Any]:
        keys = ("obs", "actions", "logp", "rewards", "dones")
        return {k: np.concatenate([b[k] for b in batches], axis=1)
                for k in keys}

    # --------------------------------------------------------- checkpoint

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        import jax

        connector_states = None
        if self.local_runner is not None:
            connector_states = self.local_runner.get_connector_states() \
                if self._has_connectors else None
        elif self._connector_states is not None:
            connector_states = self._connector_states
        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "env_steps": self._env_steps_lifetime,
                "connector_states": connector_states}

    def load_checkpoint(self, data: Any) -> None:
        import ray_tpu

        self.params = data["params"]
        self.opt_state = data["opt_state"]
        self._env_steps_lifetime = data.get("env_steps", 0)
        # a policy trained on normalized obs needs its normalizer back
        # (running stats are part of the policy, not transient state)
        states = data.get("connector_states")
        if states is not None and not self._has_connectors:
            import sys

            print("WARNING: checkpoint carries connector state "
                  "(normalizer statistics are part of the policy) but "
                  "this config has no connectors — restored policy "
                  "will see raw observations", file=sys.stderr)
        if states is not None and self._has_connectors:
            self._connector_states = states
            if self.local_runner is not None:
                self.local_runner.set_connector_states(states)
            else:
                ray_tpu.get([r.set_connector_states.remote(states)
                             for r in self.runners])

    def cleanup(self) -> None:
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    # legacy surface ------------------------------------------------------

    def _transform_obs(self, obs: np.ndarray) -> np.ndarray:
        """Apply the env-to-module pipeline for out-of-rollout inference
        (serving/eval): a policy trained on transformed observations
        must never see raw ones."""
        if self._e2m_template is None:
            return obs
        if self.local_runner is not None and \
                getattr(self.local_runner, "_env_to_module", None) \
                is not None:
            return self.local_runner._env_to_module(obs, update=False)
        states = (self._connector_states or {}).get("env_to_module")
        if states is not None:
            self._e2m_template.set_state(states)
        return self._e2m_template(obs, update=False)

    def compute_single_action(self, obs: np.ndarray) -> Any:
        """Greedy action for serving/eval (reference
        Algorithm.compute_single_action)."""
        import jax.numpy as jnp

        from . import core

        obs = np.asarray(self._transform_obs(np.asarray(obs)[None]))
        logits = core.policy_logits(self.params,
                                    jnp.asarray(obs, jnp.float32))
        if self.continuous:
            return np.asarray(logits[0])
        return int(np.argmax(np.asarray(logits[0])))


__all__ = ["Algorithm", "AlgorithmConfig"]
