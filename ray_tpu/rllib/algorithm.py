"""Algorithm base + AlgorithmConfig builder.

Reference: rllib/algorithms/algorithm.py (Algorithm extends Tune's
Trainable; step() -> training_step()) and algorithm_config.py (the
builder: .environment().env_runners().training().build()). The rebuild
keeps the builder surface and the Trainable integration (so
tune.Tuner(PPO...) works), while the learner update is a single jitted
SPMD function instead of a DDP-wrapped torch module
(torch_learner.py:265's NCCL path -> XLA collectives on the mesh).
"""
from __future__ import annotations

import collections
import copy
from typing import Any, Dict, List, Optional, Tuple, Type

import numpy as np

from ..tune.trainable import Trainable
from .env import make_env
from .env_runner import EnvRunner, make_remote_runners


class AlgorithmConfig:
    """Builder (reference algorithm_config.py)."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env: Any = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 1
        self.rollout_fragment_length = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.seed = 0
        self.hidden = (64, 64)
        self.train_extra: Dict[str, Any] = {}

    # builder steps -------------------------------------------------------

    def environment(self, env: Any = None, *,
                    env_config: Optional[Dict] = None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr: Optional[float] = None,
                 gamma: Optional[float] = None,
                 model: Optional[Dict] = None,
                 **extra) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if model and "fcnet_hiddens" in model:
            self.hidden = tuple(model["fcnet_hiddens"])
        self.train_extra.update(extra)
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "env": self.env, "env_config": self.env_config,
            "num_env_runners": self.num_env_runners,
            "num_envs_per_env_runner": self.num_envs_per_env_runner,
            "rollout_fragment_length": self.rollout_fragment_length,
            "lr": self.lr, "gamma": self.gamma, "seed": self.seed,
            "hidden": self.hidden,
        }
        d.update(self.train_extra)
        return d

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("no algo_class bound to this config")
        return self.algo_class(self.to_dict())

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)


class Algorithm(Trainable):
    """Trainable whose step() is one training iteration (reference
    algorithm.py:789 step -> :1489 training_step)."""

    _default_config: Dict[str, Any] = {}
    # value-based algorithms sample with their own policy (e.g. DQN's
    # epsilon-greedy Q-net) — override to swap the collection class
    _runner_cls: Type[EnvRunner] = EnvRunner

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(cls)
        for k, v in cls._default_config.items():
            setattr(cfg, k, v) if hasattr(cfg, k) \
                else cfg.train_extra.__setitem__(k, v)
        return cfg

    # ------------------------------------------------------------- setup

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = dict(self._default_config)
        cfg.update(config)
        self.cfg = cfg
        if cfg.get("env") is None:
            raise ValueError("config['env'] is required")
        probe = make_env(cfg["env"], 1, cfg.get("env_config"),
                         seed=cfg.get("seed", 0))
        self.obs_dim = probe.observation_dim
        self.num_actions = probe.num_actions
        self.act_dim = probe.act_dim
        self.continuous = probe.num_actions < 0

        n_runners = cfg.get("num_env_runners", 0)
        if n_runners > 0:
            self.runners = make_remote_runners(
                cfg["env"], num_runners=n_runners,
                num_envs=cfg.get("num_envs_per_env_runner", 1),
                rollout_fragment_length=cfg.get("rollout_fragment_length",
                                                128),
                env_config=cfg.get("env_config"),
                seed=cfg.get("seed", 0), runner_cls=self._runner_cls)
            self.local_runner = None
        else:
            self.runners = []
            self.local_runner = self._runner_cls(
                cfg["env"], num_envs=cfg.get("num_envs_per_env_runner", 1),
                rollout_fragment_length=cfg.get("rollout_fragment_length",
                                                128),
                seed=cfg.get("seed", 0), env_config=cfg.get("env_config"))
        self._episode_returns: collections.deque = collections.deque(
            maxlen=100)
        self._episode_lens: collections.deque = collections.deque(maxlen=100)
        self._env_steps_lifetime = 0
        self._build_learner()

    def _build_learner(self) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self) -> Dict[str, Any]:
        result = self.training_step()
        result.setdefault("episode_return_mean",
                          float(np.mean(self._episode_returns))
                          if self._episode_returns else float("nan"))
        result.setdefault("episode_len_mean",
                          float(np.mean(self._episode_lens))
                          if self._episode_lens else float("nan"))
        result.setdefault("num_env_steps_sampled_lifetime",
                          self._env_steps_lifetime)
        return result

    # ----------------------------------------------------------- sampling

    def _sample_params(self):
        """Params handed to EnvRunners — variants whose runner wants a
        different layout (SAC/CQL's {"pi", "scale"}) override this."""
        return self.params

    def _host_params(self):
        import jax

        return jax.device_get(self._sample_params())

    def _collect_batches(self) -> List[Dict[str, Any]]:
        """Synchronous fan-out (reference rollout_ops.py
        synchronous_parallel_sample)."""
        if self.local_runner is not None:
            batches = [self.local_runner.sample(self._sample_params())]
        else:
            import ray_tpu

            p = self._host_params()
            batches = ray_tpu.get(
                [r.sample.remote(p) for r in self.runners])
        for b in batches:
            self._episode_returns.extend(b["episode_returns"])
            self._episode_lens.extend(b["episode_lens"])
            self._env_steps_lifetime += int(np.prod(b["rewards"].shape))
        return batches

    @staticmethod
    def _concat_batches(batches: List[Dict[str, Any]]) -> Dict[str, Any]:
        keys = ("obs", "actions", "logp", "rewards", "dones")
        return {k: np.concatenate([b[k] for b in batches], axis=1)
                for k in keys}

    # --------------------------------------------------------- checkpoint

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        import jax

        return {"params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "env_steps": self._env_steps_lifetime}

    def load_checkpoint(self, data: Any) -> None:
        self.params = data["params"]
        self.opt_state = data["opt_state"]
        self._env_steps_lifetime = data.get("env_steps", 0)

    def cleanup(self) -> None:
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    # legacy surface ------------------------------------------------------

    def compute_single_action(self, obs: np.ndarray) -> Any:
        """Greedy action for serving/eval (reference
        Algorithm.compute_single_action)."""
        import jax.numpy as jnp

        from . import core

        logits = core.policy_logits(self.params,
                                    jnp.asarray(obs[None], jnp.float32))
        if self.continuous:
            return np.asarray(logits[0])
        return int(np.argmax(np.asarray(logits[0])))


__all__ = ["Algorithm", "AlgorithmConfig"]
