"""DQN: off-policy Q-learning with replay buffer, double-Q targets and a
periodically synced target network.

Reference: rllib/algorithms/dqn/ (dqn.py training_step = sample →
store_to_replay → sample_from_replay → learner update → target sync;
loss in dqn_rainbow_torch_learner.py: double-DQN argmax via the online
net, Huber TD error) and rllib/utils/replay_buffers/. The rebuild keeps
the replay-train shape with a flat numpy ring buffer on the host (cheap
random access; sampling feeds jnp batches into one jitted update) and an
epsilon-greedy Q EnvRunner instead of the logp-policy runner.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import core
from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import EnvRunner


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.train_extra.update({
            "buffer_capacity": 50_000, "train_batch_size": 64,
            "updates_per_step": 32, "learning_starts": 1_000,
            "target_network_update_freq": 500,
            "epsilon_initial": 1.0, "epsilon_final": 0.05,
            "epsilon_timesteps": 8_000, "grad_clip": 10.0,
        })


class ReplayBuffer:
    """Flat uniform ring buffer (reference utils/replay_buffers/
    replay_buffer.py) — numpy host-side; minibatches become device
    arrays only at update time. act_dim=0 stores discrete int actions
    (DQN); act_dim>0 stores continuous [.., act_dim] floats (SAC)."""

    def __init__(self, capacity: int, obs_dim: int, act_dim: int = 0):
        self.capacity = capacity
        self.act_dim = act_dim
        self._obs = np.empty((capacity, obs_dim), np.float32)
        self._next_obs = np.empty((capacity, obs_dim), np.float32)
        self._actions = np.empty(
            (capacity, act_dim) if act_dim else capacity,
            np.float32 if act_dim else np.int32)
        self._rewards = np.empty(capacity, np.float32)
        self._dones = np.empty(capacity, np.float32)
        self._size = 0
        self._pos = 0

    def __len__(self) -> int:
        return self._size

    def add_fragment(self, batch: Dict[str, np.ndarray]) -> None:
        """Store a [T, N] rollout fragment as T*N transitions. With
        SAME_STEP auto-reset, obs[t+1] of a done slot is the NEXT
        episode's reset obs — harmless: the (1-done) mask zeroes the
        bootstrap exactly there."""
        t1, n, d = batch["obs"].shape
        T = t1 - 1
        obs = batch["obs"][:-1].reshape(T * n, d)
        next_obs = batch["obs"][1:].reshape(T * n, d)
        actions = batch["actions"].reshape(
            (T * n, self.act_dim) if self.act_dim else T * n)
        rewards = batch["rewards"].reshape(T * n)
        dones = batch["dones"].reshape(T * n).astype(np.float32)
        m = T * n
        idx = (self._pos + np.arange(m)) % self.capacity
        self._obs[idx] = obs
        self._next_obs[idx] = next_obs
        self._actions[idx] = actions
        self._rewards[idx] = rewards
        self._dones[idx] = dones
        self._pos = int((self._pos + m) % self.capacity)
        self._size = int(min(self._size + m, self.capacity))

    def sample(self, rng: np.random.Generator, batch_size: int
               ) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self._size, batch_size)
        return {"obs": self._obs[idx], "next_obs": self._next_obs[idx],
                "actions": self._actions[idx],
                "rewards": self._rewards[idx], "dones": self._dones[idx]}


class QEnvRunner(EnvRunner):
    """EnvRunner whose policy is epsilon-greedy over the Q-network;
    `params` is {"q": mlp, "epsilon": scalar} (reference
    EpsilonGreedy exploration, utils/exploration/epsilon_greedy.py)."""

    def _build_act(self):
        @jax.jit
        def act(params, obs, key):
            q = core.mlp_apply(params["q"], obs)
            greedy = jnp.argmax(q, axis=-1)
            k1, k2 = jax.random.split(key)
            rand = jax.random.randint(k1, greedy.shape, 0, q.shape[-1])
            explore = jax.random.uniform(k2, greedy.shape) \
                < params["epsilon"]
            a = jnp.where(explore, rand, greedy)
            return a, jnp.zeros(a.shape, jnp.float32)  # logp unused

        return act


def make_dqn_update(cfg: Dict[str, Any], optimizer):
    gamma = cfg["gamma"]

    def loss_fn(params, target_params, batch):
        q = core.mlp_apply(params["q"], batch["obs"])
        qa = jnp.take_along_axis(q, batch["actions"][:, None],
                                 axis=-1)[:, 0]
        # double DQN: argmax by the ONLINE net, value by the target net
        next_online = core.mlp_apply(params["q"], batch["next_obs"])
        next_a = jnp.argmax(next_online, axis=-1)
        next_target = core.mlp_apply(target_params["q"], batch["next_obs"])
        next_q = jnp.take_along_axis(next_target, next_a[:, None],
                                     axis=-1)[:, 0]
        target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * next_q
        td = qa - jax.lax.stop_gradient(target)
        loss = optax.huber_loss(td).mean()
        return loss, {"td_error_mean": jnp.abs(td).mean(),
                      "q_mean": qa.mean()}

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def update(params, target_params, opt_state, batch):
        (loss, aux), grads = grad_fn(params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        aux["loss"] = loss
        return params, opt_state, aux

    return jax.jit(update, donate_argnums=(0, 2))


class DQN(Algorithm):
    _default_config = {
        "buffer_capacity": 50_000, "train_batch_size": 64,
        "updates_per_step": 32, "learning_starts": 1_000,
        "target_network_update_freq": 500,
        "epsilon_initial": 1.0, "epsilon_final": 0.05,
        "epsilon_timesteps": 8_000, "grad_clip": 10.0,
        "rollout_fragment_length": 32, "num_envs_per_env_runner": 8,
        "lr": 1e-3,
    }
    _runner_cls = QEnvRunner

    def _build_learner(self) -> None:
        cfg = self.cfg
        if self.continuous:
            raise ValueError("DQN requires a discrete action space")
        key = jax.random.PRNGKey(cfg.get("seed", 0))
        hidden = tuple(cfg.get("hidden", (64, 64)))
        self.params = {"q": core.mlp_init(
            key, [self.obs_dim, *hidden, self.num_actions])}
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.get("grad_clip", 10.0)),
            optax.adam(cfg.get("lr", 1e-3)))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_dqn_update(cfg, self.optimizer)
        self.buffer = ReplayBuffer(cfg.get("buffer_capacity", 50_000),
                                   self.obs_dim)
        self._np_rng = np.random.default_rng(cfg.get("seed", 0))
        self._steps_since_sync = 0

    # -- epsilon schedule ----------------------------------------------------
    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self._env_steps_lifetime
                   / max(1, cfg.get("epsilon_timesteps", 8_000)))
        e0, e1 = cfg.get("epsilon_initial", 1.0), \
            cfg.get("epsilon_final", 0.05)
        return float(e0 + frac * (e1 - e0))

    def _sample_params(self) -> Dict[str, Any]:
        # epsilon as an ARRAY, not a python float — a float would be a
        # static jit argument and recompile the act fn every schedule tick
        return {"q": self.params["q"],
                "epsilon": jnp.asarray(self._epsilon(), jnp.float32)}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        # -- collect ---------------------------------------------------------
        if self.local_runner is not None:
            batches = [self.local_runner.sample(self._sample_params())]
        else:
            import ray_tpu

            p = jax.device_get(self._sample_params())
            batches = ray_tpu.get(
                [r.sample.remote(p) for r in self.runners])
        for b in batches:
            self._episode_returns.extend(b["episode_returns"])
            self._episode_lens.extend(b["episode_lens"])
            n_new = int(np.prod(b["rewards"].shape))
            self._env_steps_lifetime += n_new
            self._steps_since_sync += n_new
            self.buffer.add_fragment(b)
        # -- learn -----------------------------------------------------------
        metrics: Dict[str, float] = {"epsilon": self._epsilon(),
                                     "buffer_size": float(len(self.buffer))}
        if len(self.buffer) < cfg.get("learning_starts", 1_000):
            return metrics
        accum = []
        for _ in range(cfg.get("updates_per_step", 32)):
            mb = self.buffer.sample(self._np_rng,
                                    cfg.get("train_batch_size", 64))
            mb = {k: jnp.asarray(v) for k, v in mb.items()}
            self.params, self.opt_state, aux = self._update(
                self.params, self.target_params, self.opt_state, mb)
            accum.append(aux)
        if self._steps_since_sync >= cfg.get("target_network_update_freq",
                                             500):
            self.target_params = jax.tree.map(jnp.copy, self.params)
            self._steps_since_sync = 0
        metrics.update({k: float(np.mean([float(a[k]) for a in accum]))
                        for k in accum[0]})
        return metrics

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        data = super().save_checkpoint(checkpoint_dir)
        data["target_params"] = jax.device_get(self.target_params)
        return data

    def load_checkpoint(self, data: Any) -> None:
        super().load_checkpoint(data)
        if "target_params" in data:
            self.target_params = data["target_params"]
        else:
            # Copy, never alias: an aliased target would track the online
            # params exactly until the next sync and re-expose the
            # donation-aliasing hazard _build_learner guards against.
            self.target_params = jax.tree.map(jnp.copy, self.params)

    def compute_single_action(self, obs: np.ndarray) -> Any:
        q = core.mlp_apply(self.params["q"],
                           jnp.asarray(obs[None], jnp.float32))
        return int(np.argmax(np.asarray(q[0])))


__all__ = ["DQN", "DQNConfig", "QEnvRunner", "ReplayBuffer",
           "make_dqn_update"]
