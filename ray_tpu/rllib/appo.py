"""APPO: asynchronous PPO — IMPALA's async sampling loop with a clipped
surrogate objective and a target network.

Reference: rllib/algorithms/appo/ (appo.py builds on IMPALA; the loss in
appo_torch_learner.py computes V-trace advantages against the TARGET
network's values, then applies the PPO clip to the importance ratio, and
the target net refreshes on an update-count interval). This is the
stated v4-32 north-star variant (SURVEY.md §7), kept in IMPALA's
async-runner shape with the update jitted end-to-end.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax

from . import core
from .algorithm import AlgorithmConfig
from .impala import IMPALA


class APPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.train_extra.update({
            "entropy_coeff": 0.01, "vf_loss_coeff": 0.5, "grad_clip": 40.0,
            "clip_rho_threshold": 1.0, "clip_c_threshold": 1.0,
            "clip_param": 0.2, "target_update_freq": 8,
            "batches_per_step": 8,
        })


def make_appo_update(cfg: Dict[str, Any], continuous: bool, optimizer):
    gamma = cfg["gamma"]
    clip_rho = cfg["clip_rho_threshold"]
    clip_c = cfg["clip_c_threshold"]
    clip = cfg["clip_param"]
    ent_coeff, vf_coeff = cfg["entropy_coeff"], cfg["vf_loss_coeff"]

    def loss_fn(params, target_params, batch):
        t1, n, d = batch["obs"].shape
        obs_flat = batch["obs"].reshape(-1, d)
        values = core.value(params, obs_flat).reshape(t1, n)
        # V-trace bootstraps from the TARGET network: advantage targets
        # stay stable across the many async updates between refreshes
        # (reference appo_torch_learner.py old-policy value path)
        target_values = core.value(target_params, obs_flat).reshape(t1, n)
        if continuous:
            mean = core.policy_logits(params, batch["obs"][:-1])
            logp = core.gaussian_logp(mean, params["log_std"],
                                      batch["actions"])
            entropy = core.gaussian_entropy(params["log_std"])
        else:
            logits = core.policy_logits(params, batch["obs"][:-1])
            logp = core.categorical_logp(logits, batch["actions"])
            entropy = core.categorical_entropy(logits).mean()
        pg_adv, vs = core.vtrace(batch["logp"], jax.lax.stop_gradient(logp),
                                 batch["rewards"], target_values,
                                 batch["dones"], gamma, clip_rho, clip_c)
        pg_adv = jax.lax.stop_gradient(pg_adv)
        vs = jax.lax.stop_gradient(vs)
        # PPO clip on the behavior→current importance ratio (the APPO
        # twist over IMPALA's plain -logp * adv)
        ratio = jnp.exp(logp - batch["logp"])
        surrogate = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1 - clip, 1 + clip) * pg_adv)
        pg_loss = -surrogate.mean()
        vf_loss = 0.5 * ((values[:-1] - vs) ** 2).mean()
        total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy,
                       "mean_ratio": ratio.mean()}

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def update(params, target_params, opt_state, batch):
        (_, aux), grads = grad_fn(params, target_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, aux

    return jax.jit(update, donate_argnums=(0, 2))


class APPO(IMPALA):
    _default_config = {
        **IMPALA._default_config,
        "clip_param": 0.2, "target_update_freq": 8,
    }

    def _build_learner(self) -> None:
        # IMPALA's init verbatim (params/optimizer/async bookkeeping);
        # only the loss and the target net differ
        super()._build_learner()
        self._update = make_appo_update(self.cfg, self.continuous,
                                        self.optimizer)
        self.target_params = jax.tree.map(jnp.copy, self.params)  # no alias:
        # params is donated in the update while target_params rides along
        self._updates_since_target = 0

    def _learn(self, b: Dict[str, Any]) -> Dict[str, float]:
        batch = {k: jnp.asarray(v) for k, v in b.items()
                 if k in ("obs", "actions", "logp", "rewards", "dones")}
        self.params, self.opt_state, aux = self._update(
            self.params, self.target_params, self.opt_state, batch)
        self._updates_since_target += 1
        if self._updates_since_target >= self.cfg.get("target_update_freq",
                                                      8):
            # COPY, not alias: params is donated to the jitted update, and
            # donating a buffer that is also passed as target_params would
            # be donating one of its own inputs
            self.target_params = jax.tree.map(jnp.copy, self.params)
            self._updates_since_target = 0
        return {k: float(v) for k, v in aux.items()}

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        data = super().save_checkpoint(checkpoint_dir)
        data["target_params"] = jax.device_get(self.target_params)
        return data

    def load_checkpoint(self, data: Any) -> None:
        super().load_checkpoint(data)
        if "target_params" in data:
            self.target_params = data["target_params"]
        else:
            # Copy, never alias (see dqn.py load_checkpoint).
            self.target_params = jax.tree.map(jnp.copy, self.params)


__all__ = ["APPO", "APPOConfig", "make_appo_update"]
