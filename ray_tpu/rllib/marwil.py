"""MARWIL: Monotonic Advantage Re-Weighted Imitation Learning.

Reference: rllib/algorithms/marwil/ (marwil.py config surface,
marwil_torch_policy loss): supervised imitation where each action's
log-likelihood is weighted by exp(beta * advantage / c), the advantage
being (return-to-go - V(s)) from a jointly-learned value head, and c a
running sqrt of the squared-advantage norm. beta=0 degrades to BC.

TPU-first shape: one jitted update step carrying (params, opt_state,
c2) — the moving normalizer lives inside the donated carry instead of a
Python-side stat, so the whole update (policy loss + value loss + norm
EMA) compiles into a single XLA program.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import core
from .offline import BC, BCConfig


class MARWILConfig(BCConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        # NB: no "gamma" here — it would shadow AlgorithmConfig.gamma in
        # to_dict() and silently pin the return-to-go discount to 0.99
        self.train_extra.update({
            "beta": 1.0, "vf_coeff": 1.0, "moving_adv_eta": 1e-2,
        })


class MARWIL(BC):
    """BC substrate (shard loading, space checks, eval harness) with the
    advantage-weighted loss and a value head."""

    _default_config = dict(BC._default_config)
    _default_config.update({
        "beta": 1.0, "vf_coeff": 1.0, "moving_adv_eta": 1e-2,
    })

    def _build_learner(self) -> None:
        cfg = self.cfg
        act_out = self.act_dim if self.continuous else self.num_actions
        hidden = tuple(cfg.get("hidden", (64, 64)))
        # policy_init's standard layout (pi + vf torsos) keeps the eval
        # EnvRunner's act function working on self.params unchanged
        self.params = core.policy_init(
            jax.random.PRNGKey(cfg.get("seed", 0)), self.obs_dim, act_out,
            hidden, continuous=self.continuous)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.get("grad_clip", 10.0)),
            optax.adam(cfg.get("lr", 1e-3)))
        self.opt_state = self.optimizer.init(self.params)
        self._c2 = jnp.asarray(1.0, jnp.float32)  # running E[adv^2]

        beta = float(cfg.get("beta", 1.0))
        vf_coeff = float(cfg.get("vf_coeff", 1.0))
        eta = float(cfg.get("moving_adv_eta", 1e-2))
        continuous = self.continuous

        def loss_fn(params, c2, batch):
            v = core.value(params, batch["obs"])
            adv = batch["returns"] - v
            # reference: squared-advantage moving norm keeps exp() stable
            c = jnp.sqrt(c2) + 1e-8
            w = jnp.exp(beta * jax.lax.stop_gradient(adv) / c)
            w = jnp.minimum(w, 20.0)  # exp blowup guard (ref clamps too)
            if continuous:
                mean = core.policy_logits(params, batch["obs"])
                logp = core.gaussian_logp(mean, params["log_std"],
                                          batch["actions"])
            else:
                logits = core.policy_logits(params, batch["obs"])
                logp = core.categorical_logp(logits, batch["actions"])
            policy_loss = -(w * logp).mean()
            value_loss = 0.5 * (adv ** 2).mean()
            total = policy_loss + vf_coeff * value_loss
            new_c2 = c2 + eta * (jax.lax.stop_gradient(
                (adv ** 2).mean()) - c2)
            return total, (policy_loss, value_loss, new_c2)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def update(params, opt_state, c2, batch):
            (_, (pl, vl, new_c2)), grads = grad_fn(params, c2, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_c2, pl, vl

        self._update = jax.jit(update, donate_argnums=(0, 1, 2))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        pls, vls = [], []
        for mb in self.data.minibatches(
                cfg.get("train_batch_size", 256),
                cfg.get("updates_per_step", 64),
                keys=("obs", "actions", "returns")):
            act_dtype = jnp.float32 if self.continuous else jnp.int32
            batch = {"obs": jnp.asarray(mb["obs"]),
                     "actions": jnp.asarray(mb["actions"], act_dtype),
                     "returns": jnp.asarray(mb["returns"])}
            self.params, self.opt_state, self._c2, pl, vl = self._update(
                self.params, self.opt_state, self._c2, batch)
            pls.append(float(pl))
            vls.append(float(vl))
        result = {"policy_loss": float(np.mean(pls)),
                  "vf_loss": float(np.mean(vls)),
                  "adv_norm": float(jnp.sqrt(self._c2))}
        result.update(self.evaluate())
        return result

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        data = super().save_checkpoint(checkpoint_dir)
        data["c2"] = float(self._c2)
        return data

    def load_checkpoint(self, data: Any) -> None:
        super().load_checkpoint(data)
        self._c2 = jnp.asarray(data.get("c2", 1.0), jnp.float32)


__all__ = ["MARWIL", "MARWILConfig"]
