"""Connector pipelines — composable obs/action transforms.

Reference: rllib/connectors/ (env-to-module and module-to-env
ConnectorV2 pipelines run inside EnvRunners: observation preprocessing
before RLModule inference, action postprocessing before env.step).
Same shape here: a ConnectorPipeline of stateless or stateful
transforms applied vectorized over [num_envs, ...] numpy arrays — the
policy trains on exactly what it saw (transformed observations are what
the rollout batch records), while logp/actions record the module's raw
output and only the env receives the transformed action.

Stateful connectors (NormalizeObservations' running mean/var) expose
get_state/set_state so runner fleets can sync and checkpoints restore.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform stage. `update=False` marks bookkeeping-only calls
    (e.g. the fragment's trailing observation) that must not advance
    running statistics twice."""

    def __call__(self, x: np.ndarray, update: bool = True) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    @staticmethod
    def merge_states(states: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Combine per-runner states into one (fleet sync); stateless
        connectors just keep the first."""
        return states[0] if states else {}

    def pop_delta(self) -> Dict[str, Any]:
        """State accumulated since the last pop — what fleet sync
        collects. Absolute states must NOT be re-merged every sync (each
        runner already holds the broadcast base; merging absolutes would
        double-count shared history, reference mean-std sync pulls
        deltas the same way). Stateless default: empty."""
        return {}


def resolve_connector(c: Any) -> Optional[Connector]:
    """Accept a Connector instance, a zero-arg factory, or None."""
    if c is None or isinstance(c, Connector):
        return c
    return c()


class ConnectorPipeline(Connector):
    def __init__(self, *connectors: Connector):
        self.connectors: List[Connector] = list(connectors)

    def __call__(self, x: np.ndarray, update: bool = True) -> np.ndarray:
        for c in self.connectors:
            x = c(x, update=update)
        return x

    def get_state(self) -> List[Dict[str, Any]]:
        return [c.get_state() for c in self.connectors]

    def set_state(self, state: List[Dict[str, Any]]) -> None:
        for c, s in zip(self.connectors, state):
            c.set_state(s)

    def merge_pipeline_states(self, states: List[List[Dict[str, Any]]]
                              ) -> List[Dict[str, Any]]:
        """Element-wise merge of per-runner pipeline states using each
        stage's merge_states."""
        return [type(c).merge_states(
            [s[i] for s in states if s[i]])
            for i, c in enumerate(self.connectors)]

    def pop_delta(self) -> List[Dict[str, Any]]:
        return [c.pop_delta() for c in self.connectors]


# ------------------------------------------------------- env-to-module

class NormalizeObservations(Connector):
    """Running mean/std normalization (reference
    connectors/env_to_module/mean_std_filter.py): batched Welford update
    over every observed vector, then (x - mean) / std clipped.

    Statistics are split into a BASE (the fleet-merged state received
    via set_state) and a local DELTA (samples seen since the last
    pop_delta), so sync rounds merge only new samples and never
    double-count shared history. Normalization always uses base+delta
    combined; get_state returns the combination (what checkpoints
    persist)."""

    def __init__(self, clip: float = 10.0, epsilon: float = 1e-8):
        self.clip = clip
        self.eps = epsilon
        self._base: Optional[Dict[str, Any]] = None
        self._d_count = 0.0
        self._d_mean: Optional[np.ndarray] = None
        self._d_m2: Optional[np.ndarray] = None

    # the properties tests/tools read: combined statistics
    @property
    def count(self) -> float:
        return self.get_state().get("count", 0.0)

    @property
    def mean(self):
        return self.get_state().get("mean")

    @property
    def m2(self):
        return self.get_state().get("m2")

    def _ensure_dim(self, dim: int) -> None:
        if self._d_mean is None:
            self._d_mean = np.zeros(dim, np.float64)
            self._d_m2 = np.zeros(dim, np.float64)

    def __call__(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        flat = obs.reshape(-1, obs.shape[-1])
        self._ensure_dim(obs.shape[-1])
        if update and len(flat):
            n_b = float(len(flat))
            mean_b = flat.mean(axis=0)
            m2_b = ((flat - mean_b) ** 2).sum(axis=0)
            delta = mean_b - self._d_mean
            total = self._d_count + n_b
            self._d_mean = self._d_mean + delta * n_b / total
            self._d_m2 = self._d_m2 + m2_b \
                + delta ** 2 * self._d_count * n_b / total
            self._d_count = total
        st = self.get_state()
        if not st.get("count"):
            return np.clip(obs, -self.clip, self.clip)
        std = np.sqrt(np.asarray(st["m2"]) / max(st["count"], 1.0)) \
            + self.eps
        out = (obs - np.asarray(st["mean"])) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def _delta_state(self) -> Dict[str, Any]:
        if self._d_mean is None or self._d_count == 0.0:
            return {}
        return {"count": self._d_count, "mean": self._d_mean.copy(),
                "m2": self._d_m2.copy()}

    def get_state(self) -> Dict[str, Any]:
        parts = [s for s in (self._base, self._delta_state()) if s]
        if not parts:
            return {"count": 0.0, "mean": None, "m2": None}
        return self.merge_states(parts)

    def set_state(self, state: Dict[str, Any]) -> None:
        self._base = dict(state) if state and state.get("mean") is not None \
            else None

    def pop_delta(self) -> Dict[str, Any]:
        d = self._delta_state()
        self._d_count = 0.0
        self._d_mean = None
        self._d_m2 = None
        return d

    @staticmethod
    def merge_states(states: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Chan et al. pairwise combine of (count, mean, M2) — the fleet
        sync the reference's mean-std filter does through the driver."""
        live = [s for s in states if s and s.get("mean") is not None]
        if not live:
            return states[0] if states else {}
        count = live[0]["count"]
        mean = np.array(live[0]["mean"], np.float64)
        m2 = np.array(live[0]["m2"], np.float64)
        for s in live[1:]:
            nb, mb = s["count"], np.asarray(s["mean"], np.float64)
            m2b = np.asarray(s["m2"], np.float64)
            delta = mb - mean
            total = count + nb
            mean = mean + delta * nb / total
            m2 = m2 + m2b + delta ** 2 * count * nb / total
            count = total
        return {"count": count, "mean": mean, "m2": m2}


class ClipObservations(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        return np.clip(obs, self.low, self.high)


# ------------------------------------------------------- module-to-env

class ClipActions(Connector):
    """Clamp continuous actions into the env's bounds (reference
    connectors/module_to_env clip_actions)."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, actions: np.ndarray,
                 update: bool = True) -> np.ndarray:
        return np.clip(actions, self.low, self.high)


class ScaleActions(Connector):
    """Affine map from the module's [-1, 1] range to env bounds."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, actions: np.ndarray,
                 update: bool = True) -> np.ndarray:
        return self.low + (np.asarray(actions) + 1.0) * 0.5 * \
            (self.high - self.low)


__all__ = ["Connector", "ConnectorPipeline", "NormalizeObservations",
           "ClipObservations", "ClipActions", "ScaleActions"]
