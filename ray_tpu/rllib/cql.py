"""CQL: Conservative Q-Learning — offline continuous control.

Reference: rllib/algorithms/cql/ (cql.py config, cql_torch_policy loss):
SAC's twin-soft-Q machinery trained purely from recorded transitions,
with a conservative penalty that pushes Q down on out-of-distribution
actions (logsumexp over sampled actions) and up on dataset actions, so
the learned policy cannot exploit over-estimated Q in states the data
never covered. The live env is an evaluation harness only.

The update is SAC's single jitted step with the penalty fused in
(sac.make_sac_update(cql=...)) — one XLA program per minibatch.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .offline import OfflineData
from .sac import SAC, SACConfig, make_sac_update


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.train_extra.update({
            "input_path": None, "cql_alpha": 1.0, "cql_n_actions": 4,
            "updates_per_step": 64,
        })

    def offline_data(self, input_path: str) -> "CQLConfig":
        self.train_extra["input_path"] = input_path
        return self


class CQL(SAC):
    """SAC substrate (networks, per-component optimizers, target sync,
    squashed-gaussian eval runner) trained from OfflineData shards."""

    _default_config = dict(SAC._default_config)
    _default_config.update({
        "input_path": None, "cql_alpha": 1.0, "cql_n_actions": 4,
        "updates_per_step": 64,
    })

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = dict(self._default_config)
        cfg.update(config)
        if not cfg.get("input_path"):
            raise ValueError("CQL needs config['input_path'] (offline "
                             "shards dir or file)")
        self.data = OfflineData(cfg["input_path"], seed=cfg.get("seed", 0),
                                gamma=cfg.get("gamma", 0.99))
        if not self.data.continuous:
            raise ValueError("CQL requires continuous-action data")
        super().setup(config)
        if self.data.obs_dim != self.obs_dim:
            raise ValueError(
                f"offline data obs_dim {self.data.obs_dim} != eval env "
                f"obs_dim {self.obs_dim}")

    def _make_update(self):
        return make_sac_update(
            self.cfg, self.act_scale, self.act_dim, self._pi_opt,
            self._q_opt, self._a_opt,
            cql={"alpha": float(self.cfg.get("cql_alpha", 1.0)),
                 "n_actions": int(self.cfg.get("cql_n_actions", 4))})

    def _build_buffer(self):
        return None  # offline: minibatches come from self.data

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        accum = []
        for mb in self.data.minibatches(
                cfg.get("train_batch_size", 256),
                cfg.get("updates_per_step", 64),
                keys=("obs", "actions", "rewards", "next_obs", "dones")):
            batch = {k: jnp.asarray(v) for k, v in mb.items()}
            self._key, sub = jax.random.split(self._key)
            self.params, self.target_q, self.opt_state, aux = \
                self._update(self.params, self.target_q, self.opt_state,
                             sub, batch)
            accum.append(aux)
        metrics = {k: float(np.mean([float(a[k]) for a in accum]))
                   for k in accum[0]}
        # evaluation rollouts: episode stats only, nothing trains on them
        self._collect_batches()
        return metrics


__all__ = ["CQL", "CQLConfig"]
