"""PPO: clipped-surrogate policy optimization with a jitted SPMD learner.

Reference: rllib/algorithms/ppo/ (ppo.py:408 training_step =
synchronous_parallel_sample -> learner_group.update_from_episodes;
torch loss in ppo_torch_learner.py). The rebuild compiles the ENTIRE
update — GAE, advantage normalization, epochs x minibatches of
clipped-surrogate SGD — into one jitted function with donated state: no
per-minibatch python, no DDP allreduce (gradients sync via XLA psum when
the batch is sharded over a dp mesh axis).
"""
from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import core
from .algorithm import Algorithm, AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.train_extra.update({
            "lambda_": 0.95, "clip_param": 0.2, "vf_clip_param": 10.0,
            "num_sgd_iter": 8, "minibatch_size": 256,
            "entropy_coeff": 0.0, "vf_loss_coeff": 0.5,
            "grad_clip": 0.5,
        })


def make_ppo_update(cfg: Dict[str, Any], continuous: bool, optimizer):
    """Build the jitted update(params, opt_state, key, batch)."""
    gamma, lam = cfg["gamma"], cfg["lambda_"]
    clip, vf_clip = cfg["clip_param"], cfg["vf_clip_param"]
    epochs, mb_size = cfg["num_sgd_iter"], cfg["minibatch_size"]
    ent_coeff, vf_coeff = cfg["entropy_coeff"], cfg["vf_loss_coeff"]

    def loss_fn(params, mb):
        if continuous:
            mean = core.policy_logits(params, mb["obs"])
            logp = core.gaussian_logp(mean, params["log_std"],
                                      mb["actions"])
            entropy = core.gaussian_entropy(params["log_std"])
        else:
            logits = core.policy_logits(params, mb["obs"])
            logp = core.categorical_logp(logits, mb["actions"])
            entropy = core.categorical_entropy(logits).mean()
        ratio = jnp.exp(logp - mb["logp"])
        adv = mb["adv"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg = -jnp.minimum(ratio * adv,
                          jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
        v = core.value(params, mb["obs"])
        vf = 0.5 * jnp.minimum((v - mb["targets"]) ** 2,
                               vf_clip ** 2).mean()
        total = pg + vf_coeff * vf - ent_coeff * entropy
        return total, {"policy_loss": pg, "vf_loss": vf,
                       "entropy": entropy,
                       "kl": (mb["logp"] - logp).mean()}

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def update(params, opt_state, key, batch):
        # batch: obs [T+1,N,D], actions [T,N(,A)], logp/rewards/dones [T,N]
        t1, n, d = batch["obs"].shape
        T = t1 - 1
        values = core.value(params, batch["obs"].reshape(-1, d)) \
            .reshape(t1, n)
        adv, targets = core.compute_gae(batch["rewards"], values,
                                        batch["dones"], gamma, lam)
        m = T * n
        flat = {
            "obs": batch["obs"][:-1].reshape(m, d),
            "actions": batch["actions"].reshape(
                (m, -1) if continuous else (m,)),
            "logp": batch["logp"].reshape(m),
            "adv": adv.reshape(m),
            "targets": targets.reshape(m),
        }
        n_mb = max(1, m // mb_size)
        usable = n_mb * (m // n_mb)

        def epoch(carry, ekey):
            params, opt_state = carry
            perm = jax.random.permutation(ekey, m)[:usable] \
                .reshape(n_mb, -1)

            def mb_step(carry, idx):
                params, opt_state = carry
                mb = jax.tree.map(lambda a: a[idx], flat)
                (_, aux), grads = grad_fn(params, mb)
                updates, opt_state = optimizer.update(grads, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), aux

            (params, opt_state), auxes = jax.lax.scan(
                mb_step, (params, opt_state), perm)
            return (params, opt_state), auxes

        (params, opt_state), auxes = jax.lax.scan(
            epoch, (params, opt_state), jax.random.split(key, epochs))
        metrics = jax.tree.map(lambda a: a.mean(), auxes)
        metrics["vf_explained_var"] = 1.0 - jnp.var(
            targets - values[:-1]) / (jnp.var(targets) + 1e-8)
        return params, opt_state, metrics

    return jax.jit(update, donate_argnums=(0, 1))


class PPO(Algorithm):
    _default_config = {
        "lambda_": 0.95, "clip_param": 0.2, "vf_clip_param": 10.0,
        "num_sgd_iter": 8, "minibatch_size": 256, "entropy_coeff": 0.0,
        "vf_loss_coeff": 0.5, "grad_clip": 0.5,
        "rollout_fragment_length": 128, "num_envs_per_env_runner": 8,
    }

    def _build_learner(self) -> None:
        cfg = self.cfg
        key = jax.random.PRNGKey(cfg.get("seed", 0))
        act_out = self.act_dim if self.continuous else self.num_actions
        self.params = core.policy_init(
            key, self.obs_dim, act_out, tuple(cfg.get("hidden", (64, 64))),
            continuous=self.continuous)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.get("grad_clip", 0.5)),
            optax.adam(cfg.get("lr", 3e-4)))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_ppo_update(cfg, self.continuous, self.optimizer)
        self._key = jax.random.PRNGKey(cfg.get("seed", 0) + 1)

    def training_step(self) -> Dict[str, Any]:
        batches = self._collect_batches()
        batch = self._concat_batches(batches)
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        self._key, sub = jax.random.split(self._key)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, sub, batch)
        return {k: float(v) for k, v in metrics.items()}


__all__ = ["PPO", "PPOConfig", "make_ppo_update"]
