"""DreamerV3 — model-based RL: learn a world model, act in imagination.

Reference: rllib/algorithms/dreamerv3/ (Hafner et al. 2023; the
reference's tf models under dreamerv3/tf/models/). Compact vector-obs
rebuild with the paper's load-bearing machinery:

- RSSM: GRU deterministic state + categorical stochastic latents with
  1% unimix and straight-through gradients; prior from h, posterior
  from (h, obs-embedding).
- Symlog observation regression, twohot-over-exponential-bins reward
  and value heads, Bernoulli continue head.
- World-model loss: prediction terms + KL balance (dyn 0.5 / rep 0.1)
  with free bits (1 nat).
- Actor-critic trained purely in imagination (lax.scan rollouts from
  posterior states), lambda-returns, return normalization by an EMA of
  the 5th-95th percentile range, entropy-regularized actor, critic with
  slow-EMA regularizer.

TPU-first shape: ONE jitted update — sequence-model scan, all heads,
KL balance, H-step imagination, lambda returns, and all three
optimizers compile into a single XLA program; the host only shuffles
replay indices. Collection runs a recurrent policy (DreamerEnvRunner
keeps per-env (h, z) and resets them on done).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import core
from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import EnvRunner

# ------------------------------------------------------------ utilities


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _twohot_bins(n: int = 41, lo: float = -20.0, hi: float = 20.0):
    return jnp.linspace(lo, hi, n)


def twohot(y, bins):
    """Two-hot encoding of symlog(y) over `bins` [n]."""
    y = jnp.clip(symlog(y), bins[0], bins[-1])
    idx = jnp.clip(jnp.searchsorted(bins, y) - 1, 0, len(bins) - 2)
    lo, hi = bins[idx], bins[idx + 1]
    w_hi = (y - lo) / jnp.maximum(hi - lo, 1e-8)
    return jax.nn.one_hot(idx, len(bins)) * (1.0 - w_hi)[..., None] \
        + jax.nn.one_hot(idx + 1, len(bins)) * w_hi[..., None]


def twohot_expectation(logits, bins):
    return symexp((jax.nn.softmax(logits, -1) * bins).sum(-1))


def twohot_loss(logits, y, bins):
    return -(twohot(y, bins) * jax.nn.log_softmax(logits, -1)).sum(-1)


# ---------------------------------------------------------------- model

GROUPS, CLASSES = 8, 8  # stochastic latent: 8 categoricals x 8 classes
STOCH = GROUPS * CLASSES


def _dense(key, sizes):
    return core.mlp_init(key, sizes)


def dreamer_init(key, obs_dim: int, num_actions: int,
                 deter: int = 128, hidden: int = 128,
                 bins: int = 41) -> Dict[str, Any]:
    ks = jax.random.split(key, 10)
    return {
        "embed": _dense(ks[0], [obs_dim, hidden, hidden]),
        # GRU over [z + one-hot action] -> deter (3 gates fused)
        "gru_x": _dense(ks[1], [STOCH + num_actions, 3 * deter]),
        "gru_h": _dense(ks[2], [deter, 3 * deter]),
        "prior": _dense(ks[3], [deter, hidden, STOCH]),
        "post": _dense(ks[4], [deter + hidden, hidden, STOCH]),
        "decoder": _dense(ks[5], [deter + STOCH, hidden, obs_dim]),
        "reward": _dense(ks[6], [deter + STOCH, hidden, bins]),
        "cont": _dense(ks[7], [deter + STOCH, hidden, 1]),
        "actor": _dense(ks[8], [deter + STOCH, hidden, num_actions]),
        "critic": _dense(ks[9], [deter + STOCH, hidden, bins]),
    }


def _gru(params, x, h):
    gates = core.mlp_apply(params["gru_x"], x) + \
        core.mlp_apply(params["gru_h"], h)
    r, u, c = jnp.split(gates, 3, -1)
    r, u = jax.nn.sigmoid(r), jax.nn.sigmoid(u)
    cand = jnp.tanh(r * c)
    return u * cand + (1.0 - u) * h


def _unimix_logits(logits, mix: float = 0.01):
    probs = jax.nn.softmax(logits.reshape(logits.shape[:-1]
                                          + (GROUPS, CLASSES)), -1)
    probs = (1.0 - mix) * probs + mix / CLASSES
    return jnp.log(probs)


def _sample_stoch(key, logits):
    """Straight-through categorical sample -> flat [.., STOCH]."""
    lp = _unimix_logits(logits)
    idx = jax.random.categorical(key, lp, -1)
    hard = jax.nn.one_hot(idx, CLASSES)
    probs = jnp.exp(lp)
    st = hard + probs - jax.lax.stop_gradient(probs)
    return st.reshape(st.shape[:-2] + (STOCH,))


def _kl_cat(lp_a, lp_b):
    """KL(a || b) for grouped categoricals given log-probs, summed over
    groups — with free bits applied by the caller."""
    pa = jnp.exp(lp_a)
    return (pa * (lp_a - lp_b)).sum(-1).sum(-1)


# ------------------------------------------------------------ algorithm


class DreamerV3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DreamerV3)
        self.train_extra.update({
            "batch_size": 16, "batch_length": 16, "horizon": 15,
            "buffer_capacity": 50_000, "updates_per_step": 4,
            "model_lr": 1e-3, "actor_lr": 3e-4, "critic_lr": 3e-4,
            "gamma": 0.985, "lam": 0.95, "ent_coef": 3e-3,
            "free_bits": 1.0, "deter": 128, "hidden": 128,
            "learning_starts": 1_000, "slow_critic_tau": 0.02,
        })


class DreamerEnvRunner(EnvRunner):
    """Recurrent collection: per-env (h, z) carried across steps and
    reset on done (reference dreamerv3 EnvRunner keeps is_first flags;
    here the state reset is explicit)."""

    def _build_act(self):
        @jax.jit
        def act(params, obs, h, key):
            k1, k2 = jax.random.split(key)
            emb = core.mlp_apply(params["embed"], symlog(obs))
            post_logits = core.mlp_apply(
                params["post"], jnp.concatenate([h, emb], -1))
            z = _sample_stoch(k1, post_logits)
            feat = jnp.concatenate([h, z], -1)
            logits = core.mlp_apply(params["actor"], feat)
            a = jax.random.categorical(k2, logits, -1)
            a_1h = jax.nn.one_hot(a, logits.shape[-1])
            h_next = _gru(params, jnp.concatenate([z, a_1h], -1), h)
            return a, h_next

        return act

    def sample(self, params: Any) -> Dict[str, Any]:
        """Base loop (env_runner.py sample) with recurrent state."""
        if self._env_to_module is not None or \
                self._module_to_env is not None:
            raise ValueError(
                "DreamerEnvRunner does not apply connector pipelines "
                "(symlog IS its observation normalization); configure "
                "DreamerV3 without env_to_module/module_to_env "
                "connectors")
        if self._act_fn is None:
            self._act_fn = self._build_act()
            self._rng_key = jax.random.PRNGKey(self._seed)
            deter = params["gru_h"][0]["w"].shape[0]
            self._h = jnp.zeros((self.env.num_envs, deter), jnp.float32)
        n, d = self.env.num_envs, self.env.observation_dim
        obs_buf = np.empty((self.T + 1, n, d), np.float32)
        act_buf = np.empty((self.T, n), np.int32)
        rew_buf = np.empty((self.T, n), np.float32)
        done_buf = np.empty((self.T, n), np.bool_)
        self._completed.clear()
        self._completed_lens.clear()
        obs = self._obs
        for t in range(self.T):
            self._rng_key, sub = jax.random.split(self._rng_key)
            a, self._h = self._act_fn(params, jnp.asarray(obs),
                                      self._h, sub)
            a = np.asarray(a)
            obs_buf[t] = obs
            act_buf[t] = a
            obs, rew, done = self.env.step(a)
            rew_buf[t] = rew
            done_buf[t] = done
            self._ep_returns += rew
            self._ep_lens += 1
            if done.any():
                mask = jnp.asarray(~done, jnp.float32)[:, None]
                self._h = self._h * mask  # reset recurrent state
                for i in np.flatnonzero(done):
                    self._completed.append(float(self._ep_returns[i]))
                    self._completed_lens.append(int(self._ep_lens[i]))
                self._ep_returns[done] = 0.0
                self._ep_lens[done] = 0
        obs_buf[self.T] = obs
        self._obs = obs
        return {"obs": obs_buf, "actions": act_buf,
                "logp": np.zeros((self.T, n), np.float32),
                "rewards": rew_buf, "dones": done_buf,
                "episode_returns": list(self._completed),
                "episode_lens": list(self._completed_lens)}


class _SeqBuffer:
    """Ring buffer of [T, N] fragments sampled as subsequences
    (reference dreamerv3 EpisodeReplayBuffer, simplified to fragments)."""

    def __init__(self, capacity_steps: int):
        self._frames: List[Dict[str, np.ndarray]] = []
        self._steps = 0
        self.cap = capacity_steps

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        self._frames.append({k: batch[k] for k in
                             ("obs", "actions", "rewards", "dones")})
        self._steps += batch["rewards"].size
        while self._steps > self.cap and len(self._frames) > 1:
            dead = self._frames.pop(0)
            self._steps -= dead["rewards"].size

    def __len__(self):
        return self._steps

    def sample(self, rng, batch: int, length: int) -> Dict[str, np.ndarray]:
        out = {k: [] for k in ("obs", "actions", "rewards", "dones")}
        for _ in range(batch):
            f = self._frames[rng.integers(len(self._frames))]
            T, N = f["rewards"].shape
            col = rng.integers(N)
            t0 = rng.integers(max(1, T - length + 1))
            sl = slice(t0, t0 + length)
            for k in out:
                seq = f[k][sl, col]
                if len(seq) < length:  # pad short tails by repetition
                    pad = np.repeat(seq[-1:], length - len(seq), 0)
                    seq = np.concatenate([seq, pad], 0)
                out[k].append(seq)
        return {k: np.stack(v, 1) for k, v in out.items()}  # [L, B, ...]


class DreamerV3(Algorithm):
    _default_config = {
        "batch_size": 16, "batch_length": 16, "horizon": 15,
        "buffer_capacity": 50_000, "updates_per_step": 4,
        "model_lr": 1e-3, "actor_lr": 3e-4, "critic_lr": 3e-4,
        "gamma": 0.985, "lam": 0.95, "ent_coef": 3e-3,
        "free_bits": 1.0, "deter": 128, "hidden": 128,
        "learning_starts": 1_000, "slow_critic_tau": 0.02,
        "rollout_fragment_length": 64, "num_envs_per_env_runner": 8,
    }
    _runner_cls = DreamerEnvRunner

    def _build_learner(self) -> None:
        cfg = self.cfg
        if self.continuous:
            raise ValueError("this DreamerV3 rebuild is discrete-action")
        self._bins = _twohot_bins()
        key = jax.random.PRNGKey(cfg.get("seed", 0))
        self.params = dreamer_init(
            key, self.obs_dim, self.num_actions,
            deter=cfg.get("deter", 128), hidden=cfg.get("hidden", 128))
        self._slow_critic = jax.tree.map(jnp.copy, self.params["critic"])
        self._ret_range = jnp.asarray(1.0)  # EMA of 5-95 pct range

        def opt(lr):
            return optax.chain(optax.clip_by_global_norm(100.0),
                               optax.adam(lr, eps=1e-8))

        wm_keys = ("embed", "gru_x", "gru_h", "prior", "post",
                   "decoder", "reward", "cont")

        def component_opt(keys, lr):
            labels = {k: jax.tree.map(
                lambda _: "on" if k in keys else "off", v)
                for k, v in self.params.items()}
            return optax.multi_transform(
                {"on": opt(lr), "off": optax.set_to_zero()}, labels)

        self._wm_opt = component_opt(wm_keys, cfg.get("model_lr", 1e-3))
        self._a_opt = component_opt({"actor"}, cfg.get("actor_lr", 3e-4))
        self._c_opt = component_opt({"critic"}, cfg.get("critic_lr", 3e-4))
        self.opt_state = {"wm": self._wm_opt.init(self.params),
                          "actor": self._a_opt.init(self.params),
                          "critic": self._c_opt.init(self.params)}
        self.buffer = _SeqBuffer(cfg.get("buffer_capacity", 50_000))
        self._np_rng = np.random.default_rng(cfg.get("seed", 0))
        self._key = jax.random.PRNGKey(cfg.get("seed", 0) + 1)
        self._update = self._make_update()

    def _make_update(self):
        cfg = self.cfg
        bins = self._bins
        gamma, lam = cfg.get("gamma", 0.985), cfg.get("lam", 0.95)
        H = cfg.get("horizon", 15)
        free = cfg.get("free_bits", 1.0)
        ent_coef = cfg.get("ent_coef", 3e-3)
        tau = cfg.get("slow_critic_tau", 0.02)
        n_act = self.num_actions

        def wm_loss(params, batch, key):
            obs = symlog(batch["obs"])                 # [L, B, D]
            a_1h = jax.nn.one_hot(batch["actions"], n_act)
            L, B = obs.shape[:2]
            emb = core.mlp_apply(params["embed"], obs)
            h0 = jnp.zeros((B, params["gru_h"][0]["w"].shape[0]))
            keys = jax.random.split(key, L)
            # is_first: reset h at episode boundaries WITHIN sampled
            # subsequences, mirroring the collector's reset-on-done
            # (reference is_first flags) — otherwise the RSSM is trained
            # to model env auto-resets as dynamics
            first = jnp.concatenate(
                [jnp.zeros((1, B)), batch["dones"][:-1]], 0)

            def step(carry, inp):
                h = carry
                emb_t, a_t, k_t, first_t = inp
                h = h * (1.0 - first_t)[:, None]
                post_logits = core.mlp_apply(
                    params["post"], jnp.concatenate([h, emb_t], -1))
                z = _sample_stoch(k_t, post_logits)
                prior_logits = core.mlp_apply(params["prior"], h)
                h_next = _gru(params, jnp.concatenate([z, a_t], -1), h)
                return h_next, (h, z, post_logits, prior_logits)

            _, (hs, zs, post_l, prior_l) = jax.lax.scan(
                step, h0, (emb, a_1h, keys, first))
            feat = jnp.concatenate([hs, zs], -1)       # [L, B, F]

            recon = core.mlp_apply(params["decoder"], feat)
            l_obs = ((recon - obs) ** 2).sum(-1)
            l_rew = twohot_loss(core.mlp_apply(params["reward"], feat),
                                batch["rewards"], bins)
            cont_logit = core.mlp_apply(params["cont"], feat)[..., 0]
            cont_target = 1.0 - batch["dones"]
            l_cont = optax.sigmoid_binary_cross_entropy(cont_logit,
                                                        cont_target)
            # KL over the SAME unimixed distributions the latents are
            # sampled from — the 1% floor also bounds the KL as the
            # posterior sharpens (reference applies unimix everywhere)
            lp_post = _unimix_logits(post_l)
            lp_prior = _unimix_logits(prior_l)
            kl_dyn = jnp.maximum(
                _kl_cat(jax.lax.stop_gradient(lp_post), lp_prior), free)
            kl_rep = jnp.maximum(
                _kl_cat(lp_post, jax.lax.stop_gradient(lp_prior)), free)
            loss = (l_obs + l_rew + l_cont
                    + 0.5 * kl_dyn + 0.1 * kl_rep).mean()
            aux = {"wm_loss": loss, "recon_loss": l_obs.mean(),
                   "kl_dyn": kl_dyn.mean(),
                   "feat": jax.lax.stop_gradient(feat)}
            return loss, aux

        def imagine(params, feat0, key):
            """H-step rollout under the model from flattened starts."""
            h = feat0[:, :params["gru_h"][0]["w"].shape[0]]
            z = feat0[:, params["gru_h"][0]["w"].shape[0]:]
            keys = jax.random.split(key, H)

            def step(carry, k_t):
                h, z = carry
                feat = jnp.concatenate([h, z], -1)
                k_a, k_z = jax.random.split(k_t)
                logits = core.mlp_apply(params["actor"], feat)
                a = jax.random.categorical(k_a, logits, -1)
                a_1h = jax.nn.one_hot(a, n_act)
                h_next = _gru(params, jnp.concatenate([z, a_1h], -1), h)
                prior_logits = core.mlp_apply(params["prior"], h_next)
                z_next = _sample_stoch(k_z, prior_logits)
                out = (feat, a, logits)
                return (h_next, z_next), out

            (_, _), (feats, acts, logitss) = jax.lax.scan(
                step, (h, z), keys)
            return feats, acts, logitss  # [H, S, ...]

        def update(params, slow_critic, ret_range, opt_state, key, batch):
            k_wm, k_im = jax.random.split(key)
            (wm_l, aux), wm_grads = jax.value_and_grad(
                wm_loss, has_aux=True)(params, batch, k_wm)
            u, opt_wm = self._wm_opt.update(wm_grads, opt_state["wm"],
                                            params)
            params = optax.apply_updates(params, u)

            # ---------------- imagination (no grads into the model)
            feat0 = aux["feat"].reshape(-1, aux["feat"].shape[-1])

            def ac_losses(p):
                feats, acts, logitss = imagine(
                    {**jax.lax.stop_gradient(
                        {k: v for k, v in p.items()
                         if k not in ("actor", "critic")}),
                     "actor": p["actor"], "critic": p["critic"]},
                    feat0, k_im)
                # reward/cont are model heads whose grads are always
                # masked off here — stop them so the backward pass never
                # builds them in the first place
                rew = twohot_expectation(core.mlp_apply(
                    jax.lax.stop_gradient(p["reward"]), feats), bins)
                cont = jax.nn.sigmoid(core.mlp_apply(
                    jax.lax.stop_gradient(p["cont"]), feats)[..., 0])
                disc = gamma * cont
                v = twohot_expectation(
                    core.mlp_apply(p["critic"], feats), bins)
                v_slow = twohot_expectation(
                    core.mlp_apply(slow_critic, feats), bins)

                # lambda returns, backwards. Alignment: rew[t]/cont[t]
                # are the heads AT feat_t (the reward/termination the
                # action taken at t causes — same alignment the world
                # model trains on), so
                #   R_t = r_t + gamma*cont_t*((1-lam) v_{t+1} + lam R_{t+1})
                def lam_step(nxt, t):
                    r_t, d_t, v_next = t
                    ret = r_t + d_t * ((1 - lam) * v_next + lam * nxt)
                    return ret, ret

                _, rets = jax.lax.scan(
                    lam_step, v[-1],
                    (rew[:-1], disc[:-1], v[1:]), reverse=True)
                rets = jax.lax.stop_gradient(rets)      # [H-1, S]
                v_tr, feats_tr = v[:-1], feats[:-1]
                logits_tr, acts_tr = logitss[:-1], acts[:-1]

                # return normalization: EMA of the 5-95 pct range
                lo, hi = jnp.percentile(rets, 5), jnp.percentile(rets, 95)
                new_range = 0.99 * ret_range + 0.01 * jnp.maximum(
                    hi - lo, 1.0)
                adv = (rets - v_tr) / jax.lax.stop_gradient(new_range)

                lp = jax.nn.log_softmax(logits_tr, -1)
                logp_a = jnp.take_along_axis(
                    lp, acts_tr[..., None], -1)[..., 0]
                entropy = -(jnp.exp(lp) * lp).sum(-1)
                actor_loss = (-jax.lax.stop_gradient(adv) * logp_a
                              - ent_coef * entropy).mean()
                critic_logits = core.mlp_apply(
                    p["critic"], jax.lax.stop_gradient(feats_tr))
                critic_loss = (
                    twohot_loss(critic_logits, rets, bins)
                    # slow-critic regularizer (reference: EMA target)
                    + twohot_loss(critic_logits,
                                  jax.lax.stop_gradient(v_slow[:-1]),
                                  bins)).mean()
                return actor_loss + critic_loss, (
                    actor_loss, critic_loss, new_range, rets.mean(),
                    entropy.mean())

            (_, (a_l, c_l, new_range, ret_mean, ent)), ac_grads = \
                jax.value_and_grad(ac_losses, has_aux=True)(params)
            u, opt_a = self._a_opt.update(ac_grads, opt_state["actor"],
                                          params)
            params = optax.apply_updates(params, u)
            u, opt_c = self._c_opt.update(ac_grads, opt_state["critic"],
                                          params)
            params = optax.apply_updates(params, u)
            slow_critic = jax.tree.map(
                lambda s, o: (1 - tau) * s + tau * o,
                slow_critic, params["critic"])
            aux_out = {"wm_loss": wm_l, "recon_loss": aux["recon_loss"],
                       "kl_dyn": aux["kl_dyn"], "actor_loss": a_l,
                       "critic_loss": c_l, "imag_return": ret_mean,
                       "entropy": ent}
            return params, slow_critic, new_range, {
                "wm": opt_wm, "actor": opt_a, "critic": opt_c}, aux_out

        return jax.jit(update, donate_argnums=(0, 1, 2, 3))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        for b in self._collect_batches():
            self.buffer.add(b)
        metrics: Dict[str, Any] = {"buffer_size": float(len(self.buffer))}
        if len(self.buffer) < cfg.get("learning_starts", 1_000):
            return metrics
        accum = []
        for _ in range(cfg.get("updates_per_step", 4)):
            mb = self.buffer.sample(self._np_rng,
                                    cfg.get("batch_size", 16),
                                    cfg.get("batch_length", 16))
            batch = {"obs": jnp.asarray(mb["obs"]),
                     "actions": jnp.asarray(mb["actions"], jnp.int32),
                     "rewards": jnp.asarray(mb["rewards"]),
                     "dones": jnp.asarray(mb["dones"], jnp.float32)}
            self._key, sub = jax.random.split(self._key)
            (self.params, self._slow_critic, self._ret_range,
             self.opt_state, aux) = self._update(
                self.params, self._slow_critic, self._ret_range,
                self.opt_state, sub, batch)
            accum.append(aux)
        metrics.update({k: float(np.mean([float(a[k]) for a in accum]))
                        for k in accum[0]})
        return metrics

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        data = super().save_checkpoint(checkpoint_dir)
        data["slow_critic"] = jax.device_get(self._slow_critic)
        data["ret_range"] = float(self._ret_range)
        return data

    def load_checkpoint(self, data: Any) -> None:
        super().load_checkpoint(data)
        if "slow_critic" in data:
            self._slow_critic = data["slow_critic"]
        else:
            self._slow_critic = jax.tree.map(jnp.copy,
                                             self.params["critic"])
        self._ret_range = jnp.asarray(data.get("ret_range", 1.0))

    def compute_single_action(self, obs: np.ndarray) -> Any:
        # one-step filtering from a zero recurrent state: adequate for
        # the near-Markov vector envs this rebuild targets
        h = jnp.zeros((1, self.params["gru_h"][0]["w"].shape[0]))
        emb = core.mlp_apply(self.params["embed"],
                             symlog(jnp.asarray(obs))[None])
        post = core.mlp_apply(self.params["post"],
                              jnp.concatenate([h, emb], -1))
        z = _sample_stoch(jax.random.PRNGKey(0), post)
        logits = core.mlp_apply(self.params["actor"],
                                jnp.concatenate([h, z], -1))
        return int(jnp.argmax(logits[0]))


__all__ = ["DreamerV3", "DreamerV3Config", "DreamerEnvRunner",
           "symlog", "symexp", "twohot", "twohot_expectation"]
