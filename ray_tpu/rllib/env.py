"""Environments: a vectorized env API + native numpy CartPole/Pendulum.

Reference surface: rllib/env/ — EnvRunners step gymnasium *vector* envs
(single_agent_env_runner.py builds `gym.vector.SyncVectorEnv`). Here the
vector API is the primitive (TPU-first: batched obs ship straight into
jitted policies), with a gymnasium adapter when the package is present
and two native numpy envs so the RL stack has zero hard deps.

Auto-reset semantics match gymnasium's VectorEnv: when an episode ends,
`step` returns the *reset* observation of the next episode and
terminated=True for that slot.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

_ENV_REGISTRY: Dict[str, Callable[..., "VectorEnv"]] = {}


def register_env(name: str, creator: Callable[..., "VectorEnv"]) -> None:
    """Reference ray/tune/registry.py register_env."""
    _ENV_REGISTRY[name] = creator


class VectorEnv:
    """num_envs parallel copies; numpy in/out."""

    num_envs: int
    observation_dim: int
    num_actions: int  # discrete; -1 => continuous action_dim in act_dim
    act_dim: int = 1

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """-> (obs [N, obs_dim], rewards [N], terminated|truncated [N])."""
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """CartPole-v1 dynamics (standard cart-pole physics: pole mass 0.1,
    cart 1.0, force 10, tau 0.02, terminate |x|>2.4 or |theta|>12deg,
    truncate at 500 steps), vectorized over N envs in numpy."""

    GRAVITY, MASSCART, MASSPOLE = 9.8, 1.0, 0.1
    LENGTH, FORCE_MAG, TAU = 0.5, 10.0, 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self.observation_dim = 4
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros(num_envs, np.int64)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, (self.num_envs, 4))
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def _reset_slots(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(-0.05, 0.05, (n, 4))
            self._steps[mask] = 0

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * theta_dot ** 2 * sintheta) \
            / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = (np.abs(x) > self.X_LIMIT) \
            | (np.abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        done = terminated | truncated
        rewards = np.ones(self.num_envs, np.float32)
        self._reset_slots(done)
        return self._state.astype(np.float32), rewards, done


class PendulumVectorEnv(VectorEnv):
    """Pendulum-v1 dynamics (g=10, m=1, l=1, dt=0.05, torque in [-2,2],
    200-step episodes), continuous actions, vectorized in numpy."""

    MAX_SPEED, MAX_TORQUE, DT = 8.0, 2.0, 0.05
    G, M, L = 10.0, 1.0, 1.0
    MAX_STEPS = 200

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self.observation_dim = 3
        self.num_actions = -1
        self.act_dim = 1
        self._rng = np.random.default_rng(seed)
        self._theta = np.zeros(num_envs)
        self._thetadot = np.zeros(num_envs)
        self._steps = np.zeros(num_envs, np.int64)

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._theta), np.sin(self._theta),
                         self._thetadot], axis=1).astype(np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._theta = self._rng.uniform(-np.pi, np.pi, self.num_envs)
        self._thetadot = self._rng.uniform(-1.0, 1.0, self.num_envs)
        self._steps[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, np.float64).reshape(self.num_envs),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th = ((self._theta + np.pi) % (2 * np.pi)) - np.pi
        costs = th ** 2 + 0.1 * self._thetadot ** 2 + 0.001 * u ** 2
        newthdot = self._thetadot + (
            3 * self.G / (2 * self.L) * np.sin(th)
            + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        newthdot = np.clip(newthdot, -self.MAX_SPEED, self.MAX_SPEED)
        self._theta = self._theta + newthdot * self.DT
        self._thetadot = newthdot
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        if done.any():
            n = int(done.sum())
            self._theta[done] = self._rng.uniform(-np.pi, np.pi, n)
            self._thetadot[done] = self._rng.uniform(-1.0, 1.0, n)
            self._steps[done] = 0
        return self._obs(), (-costs).astype(np.float32), done


class GymnasiumVectorEnv(VectorEnv):
    """Adapter over gymnasium.make_vec (reference EnvRunners' gym vector
    envs)."""

    def __init__(self, env_id: str, num_envs: int = 1, seed: int = 0):
        import gymnasium as gym
        from gymnasium.vector import AutoresetMode, SyncVectorEnv

        # gymnasium >= 1.0 defaults vector envs to NEXT_STEP autoreset (the
        # done step returns the final obs and the following step is a no-op
        # reset transition). The runner's rollout/GAE logic expects the
        # classic semantics — obs returned alongside done=True is already the
        # next episode's reset obs — so request SAME_STEP explicitly.
        self._env = SyncVectorEnv(
            [lambda: gym.make(env_id) for _ in range(num_envs)],
            autoreset_mode=AutoresetMode.SAME_STEP)
        self.num_envs = num_envs
        self._seed = seed
        space = self._env.single_observation_space
        self.observation_dim = int(np.prod(space.shape))
        act = self._env.single_action_space
        if hasattr(act, "n"):
            self.num_actions = int(act.n)
        else:
            self.num_actions = -1
            self.act_dim = int(np.prod(act.shape))

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        obs, _ = self._env.reset(seed=seed if seed is not None
                                 else self._seed)
        return obs.reshape(self.num_envs, -1).astype(np.float32)

    def step(self, actions: np.ndarray):
        obs, rew, term, trunc, _ = self._env.step(actions)
        return (obs.reshape(self.num_envs, -1).astype(np.float32),
                np.asarray(rew, np.float32),
                np.asarray(term) | np.asarray(trunc))


def make_env(env: Any, num_envs: int, env_config: Optional[Dict] = None,
             seed: int = 0) -> VectorEnv:
    env_config = dict(env_config or {})
    if callable(env) and not isinstance(env, str):
        return env(num_envs=num_envs, seed=seed, **env_config)
    if env in _ENV_REGISTRY:
        return _ENV_REGISTRY[env](num_envs=num_envs, seed=seed, **env_config)
    if env in ("CartPole-v1", "CartPole-v0"):
        return CartPoleVectorEnv(num_envs, seed=seed)
    if env in ("Pendulum-v1", "Pendulum-v0"):
        return PendulumVectorEnv(num_envs, seed=seed)
    return GymnasiumVectorEnv(env, num_envs, seed=seed)


__all__ = ["VectorEnv", "CartPoleVectorEnv", "PendulumVectorEnv",
           "GymnasiumVectorEnv", "register_env", "make_env"]
