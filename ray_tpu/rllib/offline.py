"""Offline RL: episode recording, offline datasets, behavior cloning.

Reference: rllib/offline/ (output writers recording EnvRunner samples,
JsonReader/OfflineData feeding algorithms, BC/MARWIL as the entry
algorithms). The rebuild keeps the same pipeline shape on numpy shards:
``record_batches`` writes EnvRunner fragments as .npz files,
``OfflineData`` loads/iterates them as minibatches, and ``BC`` trains a
policy by supervised action log-likelihood in one jitted update —
evaluable against a live env through the standard Algorithm surface.
"""
from __future__ import annotations

import glob
import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from . import core
from .algorithm import Algorithm, AlgorithmConfig

_KEYS = ("obs", "actions", "logp", "rewards", "dones")


def record_batches(env: Any, num_fragments: int, out_dir: str, *,
                   params: Any = None, num_envs: int = 8,
                   rollout_fragment_length: int = 64, seed: int = 0,
                   env_config: Optional[Dict] = None) -> List[str]:
    """Roll out `num_fragments` EnvRunner fragments (with `params`'
    policy, or a freshly initialized one ≈ random) and write each as an
    .npz shard (reference offline output writer). Returns the paths."""
    import jax

    from .env_runner import EnvRunner

    runner = EnvRunner(env, num_envs=num_envs,
                       rollout_fragment_length=rollout_fragment_length,
                       seed=seed, env_config=env_config)
    if params is None:
        act_out = runner.env.act_dim if runner.continuous \
            else runner.env.num_actions
        params = core.policy_init(jax.random.PRNGKey(seed),
                                  runner.env.observation_dim, act_out,
                                  continuous=runner.continuous)
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for i in range(num_fragments):
        b = runner.sample(params)
        path = os.path.join(out_dir, f"fragment_{i:05d}.npz")
        with open(path, "wb") as f:
            np.savez(f, **{k: np.asarray(b[k]) for k in _KEYS})
        paths.append(path)
    return paths


class OfflineData:
    """Flat transition view over recorded shards, iterated as shuffled
    minibatches (reference OfflineData / JsonReader).

    Besides (obs, actions) for BC, full transitions are exposed for
    offline RL: next_obs / rewards / dones (CQL) and the per-transition
    discounted return-to-go `returns` (MARWIL's advantage target),
    computed per fragment column and truncated at the fragment boundary
    (zero bootstrap — the standard offline approximation)."""

    def __init__(self, paths: Any, seed: int = 0, gamma: float = 0.99):
        if isinstance(paths, str):
            paths = sorted(glob.glob(os.path.join(paths, "*.npz"))) \
                if os.path.isdir(paths) else [paths]
        if not paths:
            raise ValueError("no offline shards found")
        obs, acts, nobs, rews, dones, rets = [], [], [], [], [], []
        have_transitions = True
        for p in paths:
            with np.load(p) as z:
                o, a = z["obs"], z["actions"]
                # obs/actions-only shards stay valid for BC — the
                # transition columns just come out as None
                r = z["rewards"].astype(np.float32) \
                    if "rewards" in z else None
                d = z["dones"].astype(np.float32) if "dones" in z else None
            t1 = o.shape[0] - 1
            n = o.shape[1]
            obs.append(o[:-1].reshape(t1 * n, -1))
            if r is None or d is None:
                have_transitions = False
            if have_transitions:
                nobs.append(o[1:].reshape(t1 * n, -1))
                rews.append(r[:t1].reshape(t1 * n))
                dones.append(d[:t1].reshape(t1 * n))
                # return-to-go per env column, truncated at fragment end
                ret = np.zeros((t1, n), np.float32)
                acc = np.zeros(n, np.float32)
                for t in reversed(range(t1)):
                    acc = r[t] + gamma * (1.0 - d[t]) * acc
                    ret[t] = acc
                rets.append(ret.reshape(t1 * n))
            # actions are [T, N] discrete or [T, N, act_dim] continuous
            acts.append(a.reshape(t1 * a.shape[1], *a.shape[2:])
                        if a.ndim > 2 else a.reshape(-1))
        self.obs = np.concatenate(obs, axis=0).astype(np.float32)
        self.actions = np.concatenate(acts, axis=0)
        if have_transitions:
            self.next_obs = np.concatenate(nobs, axis=0).astype(np.float32)
            self.rewards = np.concatenate(rews, axis=0)
            self.dones = np.concatenate(dones, axis=0)
            self.returns = np.concatenate(rets, axis=0)
        else:
            self.next_obs = self.rewards = self.dones = None
            self.returns = None
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self.obs)

    @property
    def obs_dim(self) -> int:
        return self.obs.shape[-1]

    @property
    def continuous(self) -> bool:
        return self.actions.dtype.kind == "f"

    @property
    def num_actions(self) -> int:
        return -1 if self.continuous else int(self.actions.max()) + 1

    def minibatches(self, batch_size: int, num_batches: int,
                    keys: tuple = ("obs", "actions")
                    ) -> Iterator[Dict[str, np.ndarray]]:
        missing = [k for k in keys if getattr(self, k) is None]
        if missing:
            raise ValueError(
                f"shards lack rewards/dones, so {missing} are "
                "unavailable (obs/actions-only data supports BC, not "
                "MARWIL/CQL)")
        for _ in range(num_batches):
            idx = self._rng.integers(0, len(self.obs), batch_size)
            yield {k: getattr(self, k)[idx] for k in keys}


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.train_extra.update({
            "input_path": None, "train_batch_size": 256,
            "updates_per_step": 64, "grad_clip": 10.0,
        })

    def offline_data(self, input_path: str) -> "BCConfig":
        self.train_extra["input_path"] = input_path
        return self


class BC(Algorithm):
    """Behavior cloning: maximize log pi(a|s) over the recorded data
    (reference rllib/algorithms/bc/). `env` is used for evaluation only
    — spaces come from the data itself."""

    _default_config = {
        "input_path": None, "train_batch_size": 256,
        "updates_per_step": 64, "grad_clip": 10.0, "lr": 1e-3,
        "num_envs_per_env_runner": 8, "rollout_fragment_length": 128,
    }

    def setup(self, config: Dict[str, Any]) -> None:
        # data first: BC's spaces come from the shards, the env is only
        # an evaluation harness — reuse the base setup for the runner
        cfg = dict(self._default_config)
        cfg.update(config)
        if not cfg.get("input_path"):
            raise ValueError("BC needs config['input_path'] (offline "
                             "shards dir or file)")
        self.data = OfflineData(cfg["input_path"],
                                seed=cfg.get("seed", 0),
                                gamma=cfg.get("gamma", 0.99))
        super().setup(config)
        if self.obs_dim != self.data.obs_dim:
            raise ValueError(
                f"offline data obs_dim {self.data.obs_dim} != eval env "
                f"obs_dim {self.obs_dim}")
        if self.data.continuous != self.continuous:
            raise ValueError(
                "offline data action kind "
                f"({'continuous' if self.data.continuous else 'discrete'})"
                " does not match the eval env")
        if not self.continuous and self.data.num_actions > self.num_actions:
            raise ValueError(
                f"offline data contains actions up to "
                f"{self.data.num_actions - 1} but the eval env has only "
                f"{self.num_actions} actions")

    def _build_learner(self) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.cfg
        act_out = self.act_dim if self.continuous else self.num_actions
        self.params = core.policy_init(
            jax.random.PRNGKey(cfg.get("seed", 0)), self.obs_dim, act_out,
            tuple(cfg.get("hidden", (64, 64))),
            continuous=self.continuous)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.get("grad_clip", 10.0)),
            optax.adam(cfg.get("lr", 1e-3)))
        self.opt_state = self.optimizer.init(self.params)
        continuous = self.continuous

        def loss_fn(params, batch):
            if continuous:
                mean = core.policy_logits(params, batch["obs"])
                logp = core.gaussian_logp(mean, params["log_std"],
                                          batch["actions"])
            else:
                logits = core.policy_logits(params, batch["obs"])
                logp = core.categorical_logp(logits, batch["actions"])
            return -logp.mean()

        grad_fn = jax.value_and_grad(loss_fn)

        def update(params, opt_state, batch):
            loss, grads = grad_fn(params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = jax.jit(update, donate_argnums=(0, 1))
        self._jnp = jnp

    def training_step(self) -> Dict[str, Any]:
        jnp = self._jnp
        cfg = self.cfg
        losses = []
        for mb in self.data.minibatches(cfg.get("train_batch_size", 256),
                                        cfg.get("updates_per_step", 64)):
            act_dtype = jnp.float32 if self.continuous else jnp.int32
            batch = {"obs": jnp.asarray(mb["obs"]),
                     "actions": jnp.asarray(mb["actions"], act_dtype)}
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch)
            losses.append(float(loss))
        result = {"bc_loss": float(np.mean(losses))}
        result.update(self.evaluate())
        return result

    def evaluate(self) -> Dict[str, Any]:
        """Rollouts on the eval env — the base class's fan-out handles
        both the local runner and a remote runner fleet, with the
        episode-stats bookkeeping."""
        self._collect_batches()
        return {}


__all__ = ["BC", "BCConfig", "OfflineData", "record_batches"]
