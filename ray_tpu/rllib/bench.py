"""RLlib throughput benchmark: env-steps/sec per algorithm.

Reference north star: the release criteria track sampler throughput
(env-steps/s) for the async algorithms on their tuned examples
(reference rllib/tuned_examples/, release/rllib_tests/). This emits the
same metric for this rebuild's PPO, IMPALA, and APPO on the native
vectorized CartPole — one JSON line per algorithm plus an aggregate
file. CPU numbers stand in until the bench env allows on-chip runs; the
jitted-update design means the learner side scales with the chip, while
these numbers are dominated by the numpy env stepping itself.

Run: `python -m ray_tpu.rllib.bench [--out RLLIB_BENCH.json]`
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict


def bench_algo(name: str, algo: Any, measure_steps: int = 8
               ) -> Dict[str, Any]:
    algo.step()  # compile + first rollout outside the window
    s0 = algo._env_steps_lifetime
    t0 = time.perf_counter()
    last: Dict[str, Any] = {}
    for _ in range(measure_steps):
        last = algo.step()
    dt = time.perf_counter() - t0
    stepped = algo._env_steps_lifetime - s0
    rec = {
        "algo": name,
        "env_steps_per_sec": round(stepped / dt, 1),
        "env_steps_measured": stepped,
        "seconds": round(dt, 2),
        "episode_return_mean": round(
            float(last.get("episode_return_mean", float("nan"))), 2),
    }
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. 'cpu') BEFORE backend "
                         "init — required on hosts whose default TPU "
                         "tunnel may be unavailable, where the first "
                         "jitted op would otherwise hang")
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
        # The package __init__ already ran under `python -m`; the update
        # only helps while no module-level code has touched a backend
        # yet. If one ever does, fail loudly here instead of silently
        # hanging on the first jit against an unavailable default tunnel.
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():  # pragma: no cover
            raise RuntimeError(
                "--platform came too late: a jax backend initialized "
                "during import; move the offending module-level jax use")

    from . import APPOConfig, IMPALAConfig, PPOConfig

    builders = {
        "ppo": lambda: (PPOConfig().environment("CartPole-v1")
                        .env_runners(num_env_runners=0,
                                     num_envs_per_env_runner=16,
                                     rollout_fragment_length=64)
                        .debugging(seed=0).build()),
        "impala": lambda: (IMPALAConfig().environment("CartPole-v1")
                           .env_runners(num_env_runners=0,
                                        num_envs_per_env_runner=16,
                                        rollout_fragment_length=64)
                           .debugging(seed=0).build()),
        "appo": lambda: (APPOConfig().environment("CartPole-v1")
                         .env_runners(num_env_runners=0,
                                      num_envs_per_env_runner=16,
                                      rollout_fragment_length=64)
                         .debugging(seed=0).build()),
    }
    import jax

    platform = jax.devices()[0].platform
    results = []
    for name, build in builders.items():
        rec = bench_algo(name, build(), args.steps)
        rec["platform"] = platform  # cpu stand-ins must say so
        results.append(rec)
        print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"platform": platform, "results": results}, f,
                      indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
