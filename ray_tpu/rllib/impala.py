"""IMPALA: asynchronous sampling with V-trace off-policy correction.

Reference: rllib/algorithms/impala/impala.py:667 — EnvRunners sample
continuously into queues; the learner consumes whatever is ready and
corrects for policy lag with V-trace; weights broadcast on an interval.
The rebuild keeps that async shape (outstanding sample() refs per runner,
processed as they complete) with the update jitted end-to-end; this is
the north-star async-RL workload shape of SURVEY.md §7 ("CPU env-runner
fleet feeding device learners").
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import core
from .algorithm import Algorithm, AlgorithmConfig


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.train_extra.update({
            "entropy_coeff": 0.01, "vf_loss_coeff": 0.5, "grad_clip": 40.0,
            "clip_rho_threshold": 1.0, "clip_c_threshold": 1.0,
            "batches_per_step": 8,
        })


def make_impala_update(cfg: Dict[str, Any], continuous: bool, optimizer):
    gamma = cfg["gamma"]
    clip_rho = cfg["clip_rho_threshold"]
    clip_c = cfg["clip_c_threshold"]
    ent_coeff, vf_coeff = cfg["entropy_coeff"], cfg["vf_loss_coeff"]

    def loss_fn(params, batch):
        t1, n, d = batch["obs"].shape
        T = t1 - 1
        obs_flat = batch["obs"].reshape(-1, d)
        values = core.value(params, obs_flat).reshape(t1, n)
        if continuous:
            mean = core.policy_logits(params, batch["obs"][:-1])
            logp = core.gaussian_logp(mean, params["log_std"],
                                      batch["actions"])
            entropy = core.gaussian_entropy(params["log_std"])
        else:
            logits = core.policy_logits(params, batch["obs"][:-1])
            logp = core.categorical_logp(logits, batch["actions"])
            entropy = core.categorical_entropy(logits).mean()
        pg_adv, vs = core.vtrace(batch["logp"], jax.lax.stop_gradient(logp),
                                 batch["rewards"], values, batch["dones"],
                                 gamma, clip_rho, clip_c)
        # V-trace targets are fixed regression/advantage targets: without the
        # stop_gradient the critic differentiates through its own target via
        # `values`, and pg_adv leaks critic gradients into the policy loss.
        pg_adv = jax.lax.stop_gradient(pg_adv)
        vs = jax.lax.stop_gradient(vs)
        pg_loss = -(logp * pg_adv).mean()
        vf_loss = 0.5 * ((values[:-1] - vs) ** 2).mean()
        total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def update(params, opt_state, batch):
        (_, aux), grads = grad_fn(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, aux

    return jax.jit(update, donate_argnums=(0, 1))


class IMPALA(Algorithm):
    _default_config = {
        "entropy_coeff": 0.01, "vf_loss_coeff": 0.5, "grad_clip": 40.0,
        "clip_rho_threshold": 1.0, "clip_c_threshold": 1.0,
        "batches_per_step": 8, "rollout_fragment_length": 64,
        "num_envs_per_env_runner": 8, "lr": 5e-4,
    }

    def _build_learner(self) -> None:
        cfg = self.cfg
        key = jax.random.PRNGKey(cfg.get("seed", 0))
        act_out = self.act_dim if self.continuous else self.num_actions
        self.params = core.policy_init(
            key, self.obs_dim, act_out, tuple(cfg.get("hidden", (64, 64))),
            continuous=self.continuous)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.get("grad_clip", 40.0)),
            optax.adam(cfg.get("lr", 5e-4)))
        self.opt_state = self.optimizer.init(self.params)
        self._update = make_impala_update(cfg, self.continuous,
                                          self.optimizer)
        self._inflight: Dict[Any, Any] = {}  # ref -> runner

    def training_step(self) -> Dict[str, Any]:
        n_batches = self.cfg.get("batches_per_step", 8)
        metrics_acc = []

        if self.local_runner is not None:
            # degenerate synchronous path (still V-trace corrected)
            for _ in range(n_batches):
                b = self.local_runner.sample(self.params)
                self._account(b)
                metrics_acc.append(self._learn(b))
        else:
            import ray_tpu

            # keep one outstanding sample per runner; behavior params are
            # whatever was current at launch (V-trace absorbs the lag)
            for r in self.runners:
                if r not in self._inflight.values():
                    ref = r.sample.remote(self._host_params())
                    self._inflight[ref] = r
            processed = 0
            while processed < n_batches:
                done, _ = ray_tpu.wait(list(self._inflight.keys()),
                                       num_returns=1, timeout=30.0)
                if not done:
                    break
                ref = done[0]
                runner = self._inflight.pop(ref)
                b = ray_tpu.get(ref)
                self._account(b)
                metrics_acc.append(self._learn(b))
                processed += 1
                # relaunch with fresh weights (broadcast-on-consume)
                nref = runner.sample.remote(self._host_params())
                self._inflight[nref] = runner
        out = {k: float(np.mean([m[k] for m in metrics_acc]))
               for k in metrics_acc[0]} if metrics_acc else {}
        return out

    def _account(self, b: Dict[str, Any]) -> None:
        self._episode_returns.extend(b["episode_returns"])
        self._episode_lens.extend(b["episode_lens"])
        self._env_steps_lifetime += int(np.prod(b["rewards"].shape))

    def _learn(self, b: Dict[str, Any]) -> Dict[str, float]:
        batch = {k: jnp.asarray(v) for k, v in b.items()
                 if k in ("obs", "actions", "logp", "rewards", "dones")}
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in aux.items()}


__all__ = ["IMPALA", "IMPALAConfig", "make_impala_update"]
