"""Multi-agent: env API, per-policy batch collection, and a multi-policy
PPO learner.

Reference: rllib/env/multi_agent_env.py (dict-keyed obs/action/reward
per agent), rllib/env/multi_agent_env_runner.py (per-policy sample
batches via policy_mapping_fn), and the multi-agent piece of
algorithm_config.py (.multi_agent(policies=..., policy_mapping_fn=...)).
The rebuild keeps the dict-of-agents surface over VECTORIZED envs (each
agent id owns [N]-batched slots, matching the TPU-first single-agent
runner) and trains one jitted PPO update per policy."""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .env import CartPoleVectorEnv, VectorEnv

AgentID = str
PolicyID = str


class MultiAgentVectorEnv:
    """num_envs parallel copies of a multi-agent episode; every agent
    observes/acts each step (turn-taking games can mask via rewards).
    Dict-keyed numpy in/out, like the reference MultiAgentEnv but
    batched over envs."""

    agent_ids: List[AgentID]

    def reset(self, seed: Optional[int] = None) -> Dict[AgentID, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[AgentID, np.ndarray]
             ) -> Tuple[Dict[AgentID, np.ndarray],
                        Dict[AgentID, np.ndarray],
                        Dict[AgentID, np.ndarray]]:
        """-> (obs, rewards, dones) dicts keyed by agent id."""
        raise NotImplementedError

    def agent_spec(self, agent_id: AgentID) -> Dict[str, int]:
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentVectorEnv):
    """N independent CartPole instances per agent (reference
    rllib/examples/envs/classes/multi_agent.py MultiAgentCartPole —
    the standard multi-agent smoke-test env)."""

    def __init__(self, num_agents: int = 2, num_envs: int = 1,
                 seed: int = 0):
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs: Dict[AgentID, VectorEnv] = {
            aid: CartPoleVectorEnv(num_envs, seed=seed + 97 * i)
            for i, aid in enumerate(self.agent_ids)}
        self.num_envs = num_envs

    def reset(self, seed: Optional[int] = None):
        return {aid: env.reset(None if seed is None else seed + i)
                for i, (aid, env) in enumerate(self._envs.items())}

    def step(self, actions):
        obs, rews, dones = {}, {}, {}
        for aid, env in self._envs.items():
            obs[aid], rews[aid], dones[aid] = env.step(actions[aid])
        return obs, rews, dones

    def agent_spec(self, agent_id):
        env = self._envs[agent_id]
        return {"obs_dim": env.observation_dim,
                "num_actions": env.num_actions, "act_dim": env.act_dim}


_MA_ENV_REGISTRY: Dict[str, Callable[..., MultiAgentVectorEnv]] = {
    "MultiAgentCartPole": MultiAgentCartPole,
}


def register_multi_agent_env(name: str, creator) -> None:
    _MA_ENV_REGISTRY[name] = creator


def make_multi_agent_env(env: Any, num_envs: int,
                         env_config: Optional[Dict] = None,
                         seed: int = 0) -> MultiAgentVectorEnv:
    env_config = dict(env_config or {})
    if callable(env) and not isinstance(env, str):
        return env(num_envs=num_envs, seed=seed, **env_config)
    if env in _MA_ENV_REGISTRY:
        return _MA_ENV_REGISTRY[env](num_envs=num_envs, seed=seed,
                                     **env_config)
    raise ValueError(f"unknown multi-agent env {env!r}")


class MultiAgentEnvRunner:
    """Collects per-POLICY rollout batches (reference
    multi_agent_env_runner.py): each step every agent acts with its
    mapped policy's jitted forward; at fragment end, agent buffers
    mapped to the same policy concatenate along the env axis, so the
    learner sees one [T, N_total] batch per policy."""

    def __init__(self, env: Any, *, num_envs: int = 1,
                 rollout_fragment_length: int = 128,
                 policy_mapping_fn: Optional[Callable[[AgentID],
                                                      PolicyID]] = None,
                 seed: int = 0, env_config: Optional[Dict] = None):
        self.env = make_multi_agent_env(env, num_envs, env_config,
                                        seed=seed)
        self.T = rollout_fragment_length
        self.policy_mapping_fn = policy_mapping_fn or (lambda aid: aid)
        self._seed = seed
        self._obs = self.env.reset(seed=seed)
        n = self.env.num_envs
        self._ep_ret = {a: np.zeros(n) for a in self.env.agent_ids}
        self._ep_len = {a: np.zeros(n, np.int64) for a in self.env.agent_ids}
        self._act_fns: Dict[bool, Any] = {}  # continuous? -> jitted act
        self._rng_key = None

    def policies_needed(self) -> Dict[PolicyID, Dict[str, int]]:
        """policy_id -> spec; agents mapping to one policy must agree on
        spaces (checked here, like the reference's policy validation)."""
        out: Dict[PolicyID, Dict[str, int]] = {}
        for aid in self.env.agent_ids:
            pid = self.policy_mapping_fn(aid)
            spec = self.env.agent_spec(aid)
            if pid in out and out[pid] != spec:
                raise ValueError(
                    f"agents mapped to policy {pid!r} have mismatched "
                    f"spaces: {out[pid]} vs {spec}")
            out[pid] = spec
        return out

    def _act_fn(self, pid: PolicyID, continuous: bool):
        # keyed by action-space KIND, not policy id: N same-kind policies
        # share one jitted act program instead of compiling N copies
        if continuous not in self._act_fns:
            from .env_runner import build_act_fn

            self._act_fns[continuous] = build_act_fn(continuous)
        return self._act_fns[continuous]

    def sample(self, params_by_policy: Dict[PolicyID, Any]
               ) -> Dict[PolicyID, Dict[str, Any]]:
        import jax

        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(self._seed)
        specs = self.policies_needed()
        agents = self.env.agent_ids
        n = self.env.num_envs
        buf: Dict[AgentID, Dict[str, np.ndarray]] = {}
        stats: Dict[AgentID, Tuple[list, list]] = {
            a: ([], []) for a in agents}
        for aid in agents:
            spec = self.env.agent_spec(aid)
            d = spec["obs_dim"]
            cont = spec["num_actions"] < 0
            buf[aid] = {
                "obs": np.empty((self.T + 1, n, d), np.float32),
                "actions": np.empty(
                    (self.T, n, spec["act_dim"]) if cont else (self.T, n),
                    np.float32 if cont else np.int32),
                "logp": np.empty((self.T, n), np.float32),
                "rewards": np.empty((self.T, n), np.float32),
                "dones": np.empty((self.T, n), np.bool_),
            }
        obs = self._obs
        for t in range(self.T):
            actions: Dict[AgentID, np.ndarray] = {}
            for aid in agents:
                pid = self.policy_mapping_fn(aid)
                cont = specs[pid]["num_actions"] < 0
                self._rng_key, sub = jax.random.split(self._rng_key)
                a, logp = self._act_fn(pid, cont)(
                    params_by_policy[pid], obs[aid], sub)
                a = np.asarray(a)
                buf[aid]["obs"][t] = obs[aid]
                buf[aid]["actions"][t] = a
                buf[aid]["logp"][t] = np.asarray(logp)
                actions[aid] = a
            obs, rews, dones = self.env.step(actions)
            for aid in agents:
                buf[aid]["rewards"][t] = rews[aid]
                buf[aid]["dones"][t] = dones[aid]
                self._ep_ret[aid] += rews[aid]
                self._ep_len[aid] += 1
                if dones[aid].any():
                    for i in np.flatnonzero(dones[aid]):
                        stats[aid][0].append(float(self._ep_ret[aid][i]))
                        stats[aid][1].append(int(self._ep_len[aid][i]))
                    self._ep_ret[aid][dones[aid]] = 0.0
                    self._ep_len[aid][dones[aid]] = 0
        for aid in agents:
            buf[aid]["obs"][self.T] = obs[aid]
        self._obs = obs
        # group agents by policy: concat along the env axis (axis=1)
        out: Dict[PolicyID, Dict[str, Any]] = {}
        for aid in agents:
            pid = self.policy_mapping_fn(aid)
            if pid not in out:
                out[pid] = {k: [] for k in buf[aid]}
                out[pid]["episode_returns"] = []
                out[pid]["episode_lens"] = []
                out[pid]["agent_ids"] = []
            for k in ("obs", "actions", "logp", "rewards", "dones"):
                out[pid][k].append(buf[aid][k])
            out[pid]["episode_returns"].extend(stats[aid][0])
            out[pid]["episode_lens"].extend(stats[aid][1])
            out[pid]["agent_ids"].append(aid)
        for pid in out:
            for k in ("obs", "actions", "logp", "rewards", "dones"):
                out[pid][k] = np.concatenate(out[pid][k], axis=1)
        return out


class MultiAgentPPO:
    """One jitted PPO learner per policy over MultiAgentEnvRunner batches
    (reference: PPO with config.multi_agent(policies=...,
    policy_mapping_fn=...)). Local-runner mode; the runner class itself
    is actor-compatible for a remote fleet."""

    def __init__(self, env: Any, *,
                 policy_mapping_fn: Optional[Callable[[AgentID],
                                                      PolicyID]] = None,
                 num_envs: int = 8, rollout_fragment_length: int = 64,
                 env_config: Optional[Dict] = None, seed: int = 0,
                 lr: float = 3e-4, gamma: float = 0.99,
                 hidden: Tuple[int, ...] = (64, 64), **train_extra):
        import jax
        import optax

        from . import core
        from .ppo import PPO, make_ppo_update

        self.runner = MultiAgentEnvRunner(
            env, num_envs=num_envs,
            rollout_fragment_length=rollout_fragment_length,
            policy_mapping_fn=policy_mapping_fn, seed=seed,
            env_config=env_config)
        cfg = dict(PPO._default_config)
        cfg.update({"lr": lr, "gamma": gamma, "hidden": hidden})
        cfg.update(train_extra)
        self.cfg = cfg
        self.policies: Dict[PolicyID, Dict[str, Any]] = {}
        key = jax.random.PRNGKey(seed)
        for pid, spec in sorted(self.runner.policies_needed().items()):
            key, sub = jax.random.split(key)
            continuous = spec["num_actions"] < 0
            act_out = spec["act_dim"] if continuous else spec["num_actions"]
            params = core.policy_init(sub, spec["obs_dim"], act_out,
                                      tuple(hidden), continuous=continuous)
            optimizer = optax.chain(
                optax.clip_by_global_norm(cfg.get("grad_clip", 0.5)),
                optax.adam(lr))
            self.policies[pid] = {
                "params": params,
                "opt_state": optimizer.init(params),
                "update": make_ppo_update(cfg, continuous, optimizer),
                "key": jax.random.split(sub)[0],
                "returns": collections.deque(maxlen=100),
            }

    def step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        batches = self.runner.sample(
            {pid: p["params"] for pid, p in self.policies.items()})
        result: Dict[str, Any] = {}
        for pid, b in batches.items():
            pol = self.policies[pid]
            batch = {k: jnp.asarray(v) for k, v in b.items()
                     if k in ("obs", "actions", "logp", "rewards", "dones")}
            pol["key"], sub = jax.random.split(pol["key"])
            pol["params"], pol["opt_state"], metrics = pol["update"](
                pol["params"], pol["opt_state"], sub, batch)
            pol["returns"].extend(b["episode_returns"])
            result[pid] = {
                **{k: float(v) for k, v in metrics.items()},
                "episode_return_mean": (float(np.mean(pol["returns"]))
                                        if pol["returns"] else float("nan")),
            }
        result["episode_return_mean"] = float(np.mean(
            [r["episode_return_mean"] for r in result.values()
             if isinstance(r, dict)]))
        return result


__all__ = ["MultiAgentVectorEnv", "MultiAgentCartPole",
           "MultiAgentEnvRunner", "MultiAgentPPO",
           "register_multi_agent_env", "make_multi_agent_env"]
