"""SAC: soft actor-critic for continuous control.

Reference: rllib/algorithms/sac/ (twin soft Q networks with polyak
target averaging, tanh-squashed gaussian policy with state-dependent
std, automatic entropy-coefficient tuning against a target entropy of
-act_dim; losses in sac_torch_learner.py). Kept in DQN's replay-train
shape — the host-side ring buffer feeds one jitted update covering
both critics, the actor, and the alpha dual variable.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import core
from .algorithm import Algorithm, AlgorithmConfig
from .dqn import ReplayBuffer
from .env_runner import EnvRunner

_LOG_STD_MIN, _LOG_STD_MAX = -10.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.train_extra.update({
            "buffer_capacity": 100_000, "train_batch_size": 256,
            "updates_per_step": 32, "learning_starts": 1_500,
            "tau": 0.005, "initial_alpha": 0.2, "grad_clip": 10.0,
        })


def sac_init(key: jax.Array, obs_dim: int, act_dim: int,
             hidden=(64, 64)) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        # policy head emits [mean, log_std] per action dim
        "pi": core.mlp_init(k1, [obs_dim, *hidden, 2 * act_dim]),
        "q1": core.mlp_init(k2, [obs_dim + act_dim, *hidden, 1]),
        "q2": core.mlp_init(k3, [obs_dim + act_dim, *hidden, 1]),
        "log_alpha": jnp.zeros(()),
    }


def _pi_dist(params, obs):
    out = core.mlp_apply(params["pi"], obs)
    mean, log_std = jnp.split(out, 2, axis=-1)
    log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
    return mean, log_std


def _sample_squashed(key, mean, log_std):
    """tanh-squashed gaussian sample + its log-prob (with the tanh
    jacobian correction, reference squashed_gaussian distribution)."""
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mean.shape)
    pre = mean + std * eps
    a = jnp.tanh(pre)
    logp = jnp.sum(
        -0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
        - jnp.log(1.0 - a ** 2 + 1e-6), axis=-1)
    return a, logp


def _q(params_q, obs, act):
    return core.mlp_apply(params_q, jnp.concatenate([obs, act],
                                                    axis=-1))[..., 0]


class SACEnvRunner(EnvRunner):
    """Collects with the squashed-gaussian policy scaled to the action
    bound; `params` = {"pi": mlp, "scale": float}."""

    def _build_act(self):
        @jax.jit
        def act(params, obs, key):
            mean, log_std = _pi_dist(params, obs)
            a, logp = _sample_squashed(key, mean, log_std)
            return a * params["scale"], logp

        return act


def make_sac_update(cfg: Dict[str, Any], act_scale: float, act_dim: int,
                    pi_opt, q_opt, a_opt,
                    cql: "Dict[str, Any] | None" = None):
    """SAC update step; with `cql` = {"alpha": λ, "n_actions": n} the
    critic loss gains the conservative penalty
    λ·(logsumexp_a Q(s,a) − Q(s,a_data)) over n uniform + n
    current-policy action samples per state (reference
    rllib/algorithms/cql/ cql_torch_policy loss, simplified: no
    importance-density subtraction, no lagrangian threshold)."""
    gamma, tau = cfg["gamma"], cfg["tau"]
    target_entropy = -float(act_dim)

    def update(params, target_q, opt_state, key, batch):
        obs, act = batch["obs"], batch["actions"] / act_scale
        next_obs = batch["next_obs"]
        k1, k2 = jax.random.split(key)
        alpha = jnp.exp(params["log_alpha"])

        # -- critic targets (no grad) ---------------------------------
        mean_n, log_std_n = _pi_dist(params, next_obs)
        a_n, logp_n = _sample_squashed(k1, mean_n, log_std_n)
        tq = jnp.minimum(_q(target_q["q1"], next_obs, a_n),
                         _q(target_q["q2"], next_obs, a_n))
        y = batch["rewards"] + gamma * (1.0 - batch["dones"]) * (
            tq - alpha * logp_n)
        y = jax.lax.stop_gradient(y)

        def critic_loss(p):
            q1d = _q(p["q1"], obs, act)
            q2d = _q(p["q2"], obs, act)
            loss = ((q1d - y) ** 2).mean() + ((q2d - y) ** 2).mean()
            if cql is not None:
                n = int(cql.get("n_actions", 4))
                kr, kp = jax.random.split(jax.random.fold_in(key, 7))
                obs_b = jnp.broadcast_to(obs, (n,) + obs.shape)
                rand_a = jax.random.uniform(
                    kr, (n,) + act.shape, minval=-1.0, maxval=1.0)
                mean_c, log_std_c = _pi_dist(p, obs)
                pol_a, _ = _sample_squashed(
                    kp, jnp.broadcast_to(mean_c, (n,) + mean_c.shape),
                    jnp.broadcast_to(log_std_c, (n,) + log_std_c.shape))
                pol_a = jax.lax.stop_gradient(pol_a)  # penalize Q only
                for qk, qd in (("q1", q1d), ("q2", q2d)):
                    cat = jnp.concatenate([_q(p[qk], obs_b, rand_a),
                                           _q(p[qk], obs_b, pol_a)],
                                          axis=0)  # (2n, B)
                    loss = loss + cql["alpha"] * (
                        jax.nn.logsumexp(cat, axis=0) - qd).mean()
            return loss

        def actor_loss(p):
            mean, log_std = _pi_dist(p, obs)
            a, logp = _sample_squashed(k2, mean, log_std)
            q = jnp.minimum(
                _q(jax.lax.stop_gradient(p["q1"]), obs, a),
                _q(jax.lax.stop_gradient(p["q2"]), obs, a))
            return (jnp.exp(jax.lax.stop_gradient(p["log_alpha"]))
                    * logp - q).mean(), logp

        def alpha_loss(p, logp):
            return -(p["log_alpha"] * jax.lax.stop_gradient(
                logp + target_entropy)).mean()

        c_loss, c_grads = jax.value_and_grad(critic_loss)(params)
        (a_loss, logp), a_grads = jax.value_and_grad(
            actor_loss, has_aux=True)(params)
        al_loss, al_grads = jax.value_and_grad(
            lambda p: alpha_loss(p, logp))(params)

        updates = {}
        new_opt = {}
        for name, grads, opt in (("q", c_grads, q_opt),
                                 ("pi", a_grads, pi_opt),
                                 ("alpha", al_grads, a_opt)):
            u, new_opt[name] = opt.update(grads, opt_state[name], params)
            updates[name] = u
        params = optax.apply_updates(params, updates["q"])
        params = optax.apply_updates(params, updates["pi"])
        params = optax.apply_updates(params, updates["alpha"])
        target_q = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                target_q,
                                {"q1": params["q1"], "q2": params["q2"]})
        aux = {"critic_loss": c_loss, "actor_loss": a_loss,
               "alpha": jnp.exp(params["log_alpha"]),
               "entropy": -logp.mean()}
        return params, target_q, new_opt, aux

    return jax.jit(update, donate_argnums=(0, 1, 2))


class SAC(Algorithm):
    _default_config = {
        "buffer_capacity": 100_000, "train_batch_size": 256,
        "updates_per_step": 32, "learning_starts": 1_500,
        "tau": 0.005, "grad_clip": 10.0, "lr": 3e-4,
        "rollout_fragment_length": 32, "num_envs_per_env_runner": 8,
    }
    _runner_cls = SACEnvRunner

    def _build_learner(self) -> None:
        cfg = self.cfg
        if not self.continuous:
            raise ValueError("SAC requires a continuous action space")
        # the native Pendulum env bounds torque at ±2; a generic bound
        # API would come from the env — use 2.0 unless configured
        self.act_scale = float(cfg.get("action_scale", 2.0))
        key = jax.random.PRNGKey(cfg.get("seed", 0))
        hidden = tuple(cfg.get("hidden", (64, 64)))
        self.params = sac_init(key, self.obs_dim, self.act_dim, hidden)
        self.target_q = jax.tree.map(
            jnp.copy, {"q1": self.params["q1"], "q2": self.params["q2"]})
        lr = cfg.get("lr", 3e-4)
        clip = cfg.get("grad_clip", 10.0)

        # Per-component optimizers over ONE params pytree: leaves outside
        # a component get set_to_zero (NOT optax.masked, whose unmasked
        # updates pass through as raw gradients and would corrupt the
        # other components on apply_updates).
        def component_opt(keys):
            labels = {k: jax.tree.map(
                lambda _: "on" if k in keys else "off", v)
                for k, v in self.params.items()}
            return optax.multi_transform(
                {"on": optax.chain(optax.clip_by_global_norm(clip),
                                   optax.adam(lr)),
                 "off": optax.set_to_zero()},
                labels)

        self._q_opt = component_opt({"q1", "q2"})
        self._pi_opt = component_opt({"pi"})
        self._a_opt = component_opt({"log_alpha"})
        self.opt_state = {
            "q": self._q_opt.init(self.params),
            "pi": self._pi_opt.init(self.params),
            "alpha": self._a_opt.init(self.params),
        }
        self._update = self._make_update()
        self.buffer = self._build_buffer()
        self._np_rng = np.random.default_rng(cfg.get("seed", 0))
        self._key = jax.random.PRNGKey(cfg.get("seed", 0) + 1)

    def _make_update(self):
        """Hook for variants (CQL) to augment the jitted update."""
        return make_sac_update(self.cfg, self.act_scale, self.act_dim,
                               self._pi_opt, self._q_opt, self._a_opt)

    def _build_buffer(self):
        """Hook: offline variants (CQL) train from shards, not a replay
        buffer — no point allocating 100k-capacity arrays."""
        return ReplayBuffer(self.cfg.get("buffer_capacity", 100_000),
                            self.obs_dim, act_dim=self.act_dim)

    def _sample_params(self):
        return {"pi": self.params["pi"],
                "scale": jnp.asarray(self.act_scale, jnp.float32)}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        for b in self._collect_batches():
            self.buffer.add_fragment(b)
        metrics: Dict[str, Any] = {"buffer_size": float(len(self.buffer))}
        if len(self.buffer) < cfg.get("learning_starts", 1_500):
            return metrics
        accum = []
        for _ in range(cfg.get("updates_per_step", 32)):
            mb = self.buffer.sample(self._np_rng,
                                    cfg.get("train_batch_size", 256))
            mb = {k: jnp.asarray(v) for k, v in mb.items()}
            self._key, sub = jax.random.split(self._key)
            self.params, self.target_q, self.opt_state, aux = \
                self._update(self.params, self.target_q, self.opt_state,
                             sub, mb)
            accum.append(aux)
        metrics.update({k: float(np.mean([float(a[k]) for a in accum]))
                        for k in accum[0]})
        return metrics

    def save_checkpoint(self, checkpoint_dir: str) -> Dict[str, Any]:
        data = super().save_checkpoint(checkpoint_dir)
        data["target_q"] = jax.device_get(self.target_q)
        return data

    def load_checkpoint(self, data: Any) -> None:
        super().load_checkpoint(data)
        if "target_q" in data:
            self.target_q = data["target_q"]
        else:
            # Copy, never alias (see dqn.py load_checkpoint).
            self.target_q = jax.tree.map(
                jnp.copy, {"q1": self.params["q1"],
                           "q2": self.params["q2"]})

    def compute_single_action(self, obs: np.ndarray) -> Any:
        mean, _ = _pi_dist(self.params,
                           jnp.asarray(obs[None], jnp.float32))
        return np.asarray(jnp.tanh(mean[0]) * self.act_scale)


__all__ = ["SAC", "SACConfig", "sac_init", "make_sac_update"]
