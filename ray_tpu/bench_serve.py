"""Open-loop serving load harness — the disaggregated-serving
acceptance benchmark the ROADMAP names.

Open loop means arrivals follow a SCHEDULE, not completions: requests
land at their appointed time whether or not the system has drained the
previous ones, which is what exposes head-of-line blocking, queue
growth, and the shed knee (a closed-loop client self-throttles and
hides all three). The workload shape:

- **Zipf prompt popularity** (``rank^-a``): a few hot prompts sharing a
  block-aligned system prefix dominate, so the prefill tier's prefix
  cache gets realistic reuse.
- **Arrival shapes**: ``uniform`` (constant rate), ``burst`` (groups
  arriving simultaneously — the TTFT-p99 killer), ``diurnal`` (a
  sinusoidal rate swing compressed into the run, peak ~2x the mean).
- **Slow clients**: a fraction of requests drain their token stream
  slowly (``token_sleep_s`` per token); decode must keep serving other
  requests while they linger.

Every request routes through a ``serve.disagg.DisaggRouter`` (disagg or
colocated mode — same admission control), so shedding engages before
queue depth is unbounded; sheds are counted, never retried (open loop).

The JSON record (last stdout line; ``--out`` also writes it) carries
TTFT p50/p99 ms, tokens/s, shed rate, and the KV-transfer accounting
(published vs fetched bytes, shm vs rpc split) — the one-set-of-numbers
evidence that no process materialized a full KV copy. Run tiny on CPU::

    python -m ray_tpu.bench_serve --requests 32 --arrival burst

``--cluster`` starts a local ray_tpu cluster and runs the prefill and
decode tiers as separate actor processes (real chunk-fabric transfers,
shm-accounted); without it everything runs in-process and the KV rides
the record inline (fetched_bytes 0 — the colocated-process shape).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def make_prompts(config, *, n_distinct: int = 8, block_size: int = 16,
                 sys_blocks: int = 2, seed: int = 0) -> List[List[int]]:
    """Distinct prompts sharing a block-aligned system prefix (so the
    prefix cache can bite), each with a short distinct tail."""
    rng = np.random.default_rng(seed)
    sys_prompt = rng.integers(1, config.vocab_size,
                              sys_blocks * block_size).tolist()
    return [sys_prompt + rng.integers(
        1, config.vocab_size,
        int(rng.integers(2, block_size + 1))).tolist()
        for _ in range(n_distinct)]


def arrival_offsets(n: int, rate_rps: float, shape: str,
                    burst_size: int = 8) -> List[float]:
    """Seconds-from-start arrival time of each request (open loop)."""
    if rate_rps <= 0:
        raise ValueError("rate_rps must be > 0")
    if shape == "uniform":
        return [i / rate_rps for i in range(n)]
    if shape == "burst":
        return [(i // burst_size) * (burst_size / rate_rps)
                for i in range(n)]
    if shape == "diurnal":
        # sinusoidal intensity over the run: rate(t) swings between
        # ~0.4x and ~2x the mean (one compressed "day"), integrated
        # stepwise so the schedule stays deterministic
        out, t = [], 0.0
        horizon = n / rate_rps
        for _ in range(n):
            phase = min(1.0, t / max(horizon, 1e-9))
            inst = rate_rps * (0.4 + 1.6 * np.sin(np.pi * phase) ** 2)
            out.append(t)
            t += 1.0 / inst
        return out
    raise ValueError(f"unknown arrival shape {shape!r} "
                     "(uniform|burst|diurnal)")


def run_load(router, prompts: Sequence[Sequence[int]], *,
             n_requests: int = 64, max_new_tokens: int = 8,
             rate_rps: float = 8.0, arrival: str = "uniform",
             burst_size: int = 8, zipf_a: float = 1.1,
             slow_client_frac: float = 0.0,
             token_sleep_s: float = 0.02,
             timeout_s: float = 120.0, seed: int = 0) -> Dict[str, Any]:
    """Replay the open-loop schedule against `router` and return the
    benchmark record (no JSON printing — callers compose it)."""
    from ray_tpu.serve.handle import RequestShedError

    rng = np.random.default_rng(seed)
    pop = 1.0 / np.arange(1, len(prompts) + 1) ** zipf_a
    picks = rng.choice(len(prompts), size=n_requests, p=pop / pop.sum())
    slow = rng.random(n_requests) < slow_client_frac
    offsets = arrival_offsets(n_requests, rate_rps, arrival, burst_size)

    lock = threading.Lock()
    ttfts: List[float] = []
    tokens = [0] * n_requests
    outcomes = {"ok": 0, "shed": 0, "error": 0}
    errors: List[str] = []

    def one(i: int) -> None:
        t0 = time.perf_counter()
        first: List[float] = []
        try:
            toks = router.generate(
                prompts[int(picks[i])], max_new_tokens,
                timeout_s=timeout_s,
                on_first_token=lambda: first.append(
                    time.perf_counter() - t0),
                token_sleep_s=token_sleep_s if slow[i] else 0.0)
            with lock:
                outcomes["ok"] += 1
                tokens[i] = len(toks)
                if first:
                    ttfts.append(first[0])
        except RequestShedError:
            with lock:
                outcomes["shed"] += 1
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            with lock:
                outcomes["error"] += 1
                if len(errors) < 5:
                    errors.append(f"{type(e).__name__}: {str(e)[:120]}")

    t_start = time.perf_counter()
    threads: List[threading.Thread] = []
    for i in range(n_requests):
        delay = offsets[i] - (time.perf_counter() - t_start)
        if delay > 0:
            time.sleep(delay)  # open loop: fire on schedule, not drain
        th = threading.Thread(target=one, args=(i,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start

    # ONE locked snapshot for the whole record: wedged request threads
    # outlive their join timeout (daemon) and may still be mutating the
    # outcome state while the record is built. hung is derived from the
    # same view — every request thread records exactly one outcome
    # before exiting, so completed+shed+errors+hung == n_requests holds
    # by construction and a smaller population can never go unreported.
    with lock:
        snap = dict(outcomes)
        total_tokens = int(sum(tokens))
        ttft_ms = sorted(t * 1e3 for t in ttfts)
        err_samples = list(errors)
    hung = n_requests - sum(snap.values())
    pct = (lambda p: round(float(np.percentile(ttft_ms, p)), 2)
           if ttft_ms else None)
    rec: Dict[str, Any] = {
        "n_requests": n_requests,
        "arrival": arrival,
        "rate_rps": rate_rps,
        "zipf_a": zipf_a,
        "max_new_tokens": max_new_tokens,
        "slow_client_frac": slow_client_frac,
        "completed": snap["ok"],
        "shed": snap["shed"],
        "errors": snap["error"],
        "shed_rate": round(snap["shed"] / n_requests, 4),
        "ttft_p50_ms": pct(50),
        "ttft_p99_ms": pct(99),
        "tokens_total": total_tokens,
        "tokens_per_sec": round(total_tokens / wall, 1) if wall else 0.0,
        "wall_s": round(wall, 3),
    }
    if hung:
        rec["hung"] = hung
    if err_samples:
        rec["error_samples"] = err_samples
    return rec


def collect_kv_accounting(prefill: Sequence[Any],
                          decode: Sequence[Any]) -> Dict[str, int]:
    """Sum the tiers' transfer counters (local objects or actors) —
    the record's no-full-copy evidence."""
    from ray_tpu.serve.disagg import _call

    out = {"transfers": 0, "published_transfers": 0,
           "published_bytes": 0, "fetched_bytes": 0,
           "shm_bytes": 0, "rpc_bytes": 0}
    for p in prefill:
        s = _call(p, "stats")
        out["published_transfers"] += int(s.get("published_transfers", 0))
        out["published_bytes"] += int(s.get("published_bytes", 0))
    for d in decode:
        s = _call(d, "stats")
        out["transfers"] += int(s.get("transfers", 0))
        out["fetched_bytes"] += int(s.get("kv_fetched_bytes", 0))
        out["shm_bytes"] += int(s.get("shm_bytes", 0))
        out["rpc_bytes"] += int(s.get("rpc_bytes", 0))
    return out


def _build_tiers(params, config, args, use_cluster: bool):
    """(router, prefill_list, decode_list, cleanup) for one mode."""
    from ray_tpu.serve.disagg import (DecodeServer, DisaggRouter,
                                      PrefillServer)

    # retention must cover every transfer that can be legitimately
    # in flight (held from publish until the router acks after decode):
    # decode_replicas * (capacity + queue depth), and affinity can
    # route ALL of them to ONE prefill server — a smaller window would
    # reap chunks a decode replica is about to fetch, failing requests
    # under exactly the burst load the harness measures
    retain = max(32, 2 * args.decode_replicas
                 * (args.max_batch + args.queue_depth))
    kw = dict(kv_block_size=args.block_size,
              kv_pool_blocks=args.pool_blocks, retain=retain)
    if use_cluster:
        import ray_tpu

        prefill = [ray_tpu.remote(PrefillServer).options(
            max_concurrency=8).remote(params, config, **kw)
            for _ in range(args.prefill_replicas)]
        decode = [ray_tpu.remote(DecodeServer).options(
            max_concurrency=args.max_batch + 4).remote(
                params, config, max_batch=args.max_batch)
            for _ in range(args.decode_replicas)]
        import ray_tpu as _rt
        for a in prefill + decode:  # fail fast on a broken __init__
            _rt.get(a.stats.remote(), timeout=120.0)
    else:
        prefill = [PrefillServer(params, config, **kw)
                   for _ in range(args.prefill_replicas)]
        decode = [DecodeServer(params, config, max_batch=args.max_batch)
                  for _ in range(args.decode_replicas)]
    router = DisaggRouter(decode=decode, prefill=prefill,
                          max_queue_depth=args.queue_depth,
                          affinity_tokens=args.block_size)

    def cleanup():
        if use_cluster:
            import ray_tpu

            for a in prefill + decode:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001 — already gone
                    pass
        else:
            for d in decode:
                d.stop()

    return router, prefill, decode, cleanup


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop disaggregated-serving load harness")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--rate", type=float, default=8.0,
                    help="mean arrival rate, requests/s")
    ap.add_argument("--arrival", default="burst",
                    choices=["uniform", "burst", "diurnal"])
    ap.add_argument("--burst-size", type=int, default=8)
    ap.add_argument("--zipf-a", type=float, default=1.1)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--slow-frac", type=float, default=0.125,
                    help="fraction of slow clients (token-paced drain)")
    ap.add_argument("--token-sleep", type=float, default=0.02)
    ap.add_argument("--distinct", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--pool-blocks", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--prefill-replicas", type=int, default=1)
    ap.add_argument("--decode-replicas", type=int, default=1)
    ap.add_argument("--queue-depth", type=int, default=8,
                    help="router backlog bound per decode replica")
    ap.add_argument("--cluster", action="store_true",
                    help="run the tiers as actors on a local cluster "
                         "(real chunk-fabric transfers)")
    ap.add_argument("--colocated-baseline", action="store_true",
                    help="also run the single-engine colocated path "
                         "for comparison")
    ap.add_argument("--out", default="", help="also write JSON here")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    import jax

    from ray_tpu.models.llama import LlamaConfig, llama_init

    config = LlamaConfig.tiny()
    params = llama_init(config, jax.random.PRNGKey(args.seed))
    prompts = make_prompts(config, n_distinct=args.distinct,
                           block_size=args.block_size, seed=args.seed)

    use_cluster = args.cluster
    if use_cluster:
        import ray_tpu

        ray_tpu.init(num_cpus=max(4, args.prefill_replicas
                                  + args.decode_replicas + 2),
                     _system_config={"log_to_driver": 0},
                     ignore_reinit_error=True)
    record: Dict[str, Any] = {
        "metric": "disagg_serve_load",
        "platform": jax.devices()[0].platform,
        "cluster": use_cluster,
        "prefill_replicas": args.prefill_replicas,
        "decode_replicas": args.decode_replicas,
        "max_batch": args.max_batch,
        "queue_depth": args.queue_depth,
    }
    load_kw = dict(n_requests=args.requests, max_new_tokens=args.max_new,
                   rate_rps=args.rate, arrival=args.arrival,
                   burst_size=args.burst_size, zipf_a=args.zipf_a,
                   slow_client_frac=args.slow_frac,
                   token_sleep_s=args.token_sleep, seed=args.seed)
    try:
        router, prefill, decode, cleanup = _build_tiers(
            params, config, args, use_cluster)
        try:
            # warm the compile caches off the clock: each distinct
            # prompt shape costs one prefill compile on first sight.
            # Snapshot the counters after warm-up so the recorded
            # accounting covers exactly the measured open-loop run —
            # published==fetched must cross-check against n_requests'
            # expected KV bytes, not n_requests + warm-up traffic.
            for p in prompts:
                router.generate(p, 2)
            warm_kv = collect_kv_accounting(prefill, decode)
            warm_rt = router.stats()
            record["disagg"] = run_load(router, prompts, **load_kw)
            kv = collect_kv_accounting(prefill, decode)
            record["disagg"]["kv_transfer"] = {
                k: v - warm_kv.get(k, 0) for k, v in kv.items()}
            record["disagg"]["router"] = {
                k: (v - warm_rt[k]
                    if k in ("dispatched", "completed", "shed") else v)
                for k, v in router.stats().items()}
            router.publish_telemetry(force=True)
        finally:
            cleanup()
        if args.colocated_baseline:
            from ray_tpu.models.engine import ContinuousBatchingEngine
            from ray_tpu.serve.disagg import DisaggRouter

            eng = ContinuousBatchingEngine(
                params, config, max_batch=args.max_batch,
                kv_block_size=args.block_size,
                kv_pool_blocks=args.pool_blocks)
            try:
                colo = DisaggRouter(colocated=eng,
                                    max_queue_depth=args.queue_depth)
                for p in prompts:
                    colo.generate(p, 2)
                warm_rt = colo.stats()
                record["colocated"] = run_load(colo, prompts, **load_kw)
                record["colocated"]["kv_transfer"] = {
                    "transfers": 0, "published_bytes": 0,
                    "fetched_bytes": 0, "shm_bytes": 0, "rpc_bytes": 0}
                record["colocated"]["router"] = {
                    k: (v - warm_rt[k]
                        if k in ("dispatched", "completed", "shed")
                        else v)
                    for k, v in colo.stats().items()}
            finally:
                eng.stop()
        # the headline numbers are the disagg run's
        top = record["disagg"]
        record.update(value=top["tokens_per_sec"], unit="tokens/s",
                      ttft_p50_ms=top["ttft_p50_ms"],
                      ttft_p99_ms=top["ttft_p99_ms"],
                      shed_rate=top["shed_rate"])
    finally:
        if use_cluster:
            import ray_tpu

            ray_tpu.shutdown()
    line = json.dumps(record)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
