"""Open-loop serving load harness — the disaggregated-serving
acceptance benchmark the ROADMAP names.

Open loop means arrivals follow a SCHEDULE, not completions: requests
land at their appointed time whether or not the system has drained the
previous ones, which is what exposes head-of-line blocking, queue
growth, and the shed knee (a closed-loop client self-throttles and
hides all three). The workload shape:

- **Zipf prompt popularity** (``rank^-a``): a few hot prompts sharing a
  block-aligned system prefix dominate, so the prefill tier's prefix
  cache gets realistic reuse.
- **Arrival shapes**: ``uniform`` (constant rate), ``burst`` (groups
  arriving simultaneously — the TTFT-p99 killer), ``diurnal`` (a
  sinusoidal rate swing compressed into the run, peak ~2x the mean).
- **Slow clients**: a fraction of requests drain their token stream
  slowly (``token_sleep_s`` per token); decode must keep serving other
  requests while they linger.

Every request routes through a ``serve.disagg.DisaggRouter`` (disagg or
colocated mode — same admission control), so shedding engages before
queue depth is unbounded; sheds are counted, never retried (open loop).

The JSON record (last stdout line; ``--out`` also writes it) carries
TTFT p50/p99 ms, tokens/s, shed rate, and the KV-transfer accounting
(published vs fetched bytes, shm vs rpc split) — the one-set-of-numbers
evidence that no process materialized a full KV copy. Run tiny on CPU::

    python -m ray_tpu.bench_serve --requests 32 --arrival burst

``--cluster`` starts a local ray_tpu cluster and runs the prefill and
decode tiers as separate actor processes (real chunk-fabric transfers,
shm-accounted); without it everything runs in-process and the KV rides
the record inline (fetched_bytes 0 — the colocated-process shape).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def make_prompts(config, *, n_distinct: int = 8, block_size: int = 16,
                 sys_blocks: int = 2, seed: int = 0) -> List[List[int]]:
    """Distinct prompts sharing a block-aligned system prefix (so the
    prefix cache can bite), each with a short distinct tail."""
    rng = np.random.default_rng(seed)
    sys_prompt = rng.integers(1, config.vocab_size,
                              sys_blocks * block_size).tolist()
    return [sys_prompt + rng.integers(
        1, config.vocab_size,
        int(rng.integers(2, block_size + 1))).tolist()
        for _ in range(n_distinct)]


def arrival_offsets(n: int, rate_rps: float, shape: str,
                    burst_size: int = 8) -> List[float]:
    """Seconds-from-start arrival time of each request (open loop)."""
    if rate_rps <= 0:
        raise ValueError("rate_rps must be > 0")
    if shape == "uniform":
        return [i / rate_rps for i in range(n)]
    if shape == "burst":
        return [(i // burst_size) * (burst_size / rate_rps)
                for i in range(n)]
    if shape == "diurnal":
        # sinusoidal intensity over the run: rate(t) swings between
        # ~0.4x and ~2x the mean (one compressed "day"), integrated
        # stepwise so the schedule stays deterministic
        out, t = [], 0.0
        horizon = n / rate_rps
        for _ in range(n):
            phase = min(1.0, t / max(horizon, 1e-9))
            inst = rate_rps * (0.4 + 1.6 * np.sin(np.pi * phase) ** 2)
            out.append(t)
            t += 1.0 / inst
        return out
    raise ValueError(f"unknown arrival shape {shape!r} "
                     "(uniform|burst|diurnal)")


def run_load(router, prompts: Sequence[Sequence[int]], *,
             n_requests: int = 64, max_new_tokens: int = 8,
             rate_rps: float = 8.0, arrival: str = "uniform",
             burst_size: int = 8, zipf_a: float = 1.1,
             slow_client_frac: float = 0.0,
             token_sleep_s: float = 0.02,
             timeout_s: float = 120.0,
             deadline_s: Optional[float] = None,
             outputs: Optional[Dict[int, List[int]]] = None,
             tenants: Optional[Sequence[str]] = None,
             tenant_zipf: float = 1.1,
             samples: Optional[List[Dict[str, Any]]] = None,
             seed: int = 0) -> Dict[str, Any]:
    """Replay the open-loop schedule against `router` and return the
    benchmark record (no JSON printing — callers compose it).
    `deadline_s` propagates a per-request deadline (sheds past it carry
    cause "deadline" — slow clients exercise exactly that edge).
    `outputs`, when given, collects each completed request's token list
    by request index — the chaos harness diffs it against a clean run's
    to prove failed-over requests stayed bit-identical.
    `tenants` (multi-tenant LoRA): each request carries a tenant tag
    drawn Zipf(`tenant_zipf`) over the list — hot tenants dominate, the
    tail pages through the adapter pool. `samples`, when given,
    collects one per-request dict (index, tenant, arrival offset, ttft)
    — the publish-no-stall analysis slices these."""
    from ray_tpu.observability import requests as reqtrace
    from ray_tpu.serve.handle import RequestShedError

    # flight-recorder window start: the record embeds the p99
    # attribution and slowest-request phase breakdowns computed over
    # ONLY this run's traces (warm-up traffic is excluded by seq)
    trace_store = reqtrace.store() if reqtrace.enabled() else None
    trace_seq0 = trace_store.seq() if trace_store is not None else 0

    rng = np.random.default_rng(seed)
    pop = 1.0 / np.arange(1, len(prompts) + 1) ** zipf_a
    picks = rng.choice(len(prompts), size=n_requests, p=pop / pop.sum())
    if tenants:
        tpop = 1.0 / np.arange(1, len(tenants) + 1) ** tenant_zipf
        tpicks = rng.choice(len(tenants), size=n_requests,
                            p=tpop / tpop.sum())
    slow = rng.random(n_requests) < slow_client_frac
    offsets = arrival_offsets(n_requests, rate_rps, arrival, burst_size)

    lock = threading.Lock()
    ttfts: List[float] = []
    latencies: List[float] = []
    tokens = [0] * n_requests
    outcomes = {"ok": 0, "shed": 0, "error": 0}
    shed_causes: Dict[str, int] = {}
    errors: List[str] = []

    def one(i: int) -> None:
        t0 = time.perf_counter()
        first: List[float] = []
        tenant = tenants[int(tpicks[i])] if tenants else None
        try:
            toks = router.generate(
                prompts[int(picks[i])], max_new_tokens,
                timeout_s=timeout_s,
                deadline_s=deadline_s,
                on_first_token=lambda: first.append(
                    time.perf_counter() - t0),
                token_sleep_s=token_sleep_s if slow[i] else 0.0,
                tenant=tenant)
            wall = time.perf_counter() - t0
            with lock:
                outcomes["ok"] += 1
                tokens[i] = len(toks)
                latencies.append(wall)
                if first:
                    ttfts.append(first[0])
                if outputs is not None:
                    outputs[i] = list(toks)
                if samples is not None:
                    samples.append({
                        "i": i, "tenant": tenant,
                        "prompt": int(picks[i]),
                        "offset_s": offsets[i],
                        "ttft_ms": first[0] * 1e3 if first else None})
        except RequestShedError as e:
            # a shed WITHOUT a cause is a regression the chaos verdict
            # must catch — never default it to a legitimate cause
            cause = getattr(e, "cause", None) or "unattributed"
            with lock:
                outcomes["shed"] += 1
                shed_causes[cause] = shed_causes.get(cause, 0) + 1
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            with lock:
                outcomes["error"] += 1
                if len(errors) < 5:
                    errors.append(f"{type(e).__name__}: {str(e)[:120]}")

    t_start = time.perf_counter()
    threads: List[threading.Thread] = []
    for i in range(n_requests):
        delay = offsets[i] - (time.perf_counter() - t_start)
        if delay > 0:
            time.sleep(delay)  # open loop: fire on schedule, not drain
        th = threading.Thread(target=one, args=(i,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start

    # ONE locked snapshot for the whole record: wedged request threads
    # outlive their join timeout (daemon) and may still be mutating the
    # outcome state while the record is built. hung is derived from the
    # same view — every request thread records exactly one outcome
    # before exiting, so completed+shed+errors+hung == n_requests holds
    # by construction and a smaller population can never go unreported.
    with lock:
        snap = dict(outcomes)
        total_tokens = int(sum(tokens))
        ttft_ms = sorted(t * 1e3 for t in ttfts)
        lat_ms = sorted(t * 1e3 for t in latencies)
        causes = dict(shed_causes)
        err_samples = list(errors)
    hung = n_requests - sum(snap.values())
    pct = (lambda p: round(float(np.percentile(ttft_ms, p)), 2)
           if ttft_ms else None)
    lpct = (lambda p: round(float(np.percentile(lat_ms, p)), 2)
            if lat_ms else None)
    rec: Dict[str, Any] = {
        "n_requests": n_requests,
        "arrival": arrival,
        "rate_rps": rate_rps,
        "zipf_a": zipf_a,
        **({"tenants": len(tenants), "tenant_zipf": tenant_zipf}
           if tenants else {}),
        "max_new_tokens": max_new_tokens,
        "slow_client_frac": slow_client_frac,
        "completed": snap["ok"],
        "shed": snap["shed"],
        "errors": snap["error"],
        "shed_rate": round(snap["shed"] / n_requests, 4),
        "shed_causes": causes,
        "ttft_p50_ms": pct(50),
        "ttft_p99_ms": pct(99),
        "latency_p50_ms": lpct(50),
        "latency_p99_ms": lpct(99),
        "tokens_total": total_tokens,
        "tokens_per_sec": round(total_tokens / wall, 1) if wall else 0.0,
        "wall_s": round(wall, 3),
    }
    if deadline_s is not None:
        rec["deadline_s"] = deadline_s
    if hung:
        rec["hung"] = hung
    if err_samples:
        rec["error_samples"] = err_samples
    if trace_store is not None:
        # per-request tail attribution over this run's traces: which
        # phase owns the p50->p99 gap, plus the five slowest requests'
        # full phase breakdowns — the BENCH_* record names the tail
        # owner instead of just reporting that a tail exists
        run_traces = trace_store.summaries_since(trace_seq0)
        if run_traces:
            slowest = sorted(run_traces,
                             key=lambda s: -s.get("total_ms", 0.0))[:5]
            rec["request_trace"] = {
                "n_traced": len(run_traces),
                "p99_attribution": reqtrace.p99_attribution(run_traces),
                "slowest": [
                    {"request_id": s.get("request_id"),
                     "total_ms": round(s.get("total_ms", 0.0), 2),
                     "outcome": s.get("outcome"),
                     "attempts": s.get("attempts", 1),
                     "phase_ms": {k: round(v, 2) for k, v in
                                  (s.get("phase_ms") or {}).items()}}
                    for s in slowest],
            }
    return rec


def collect_kv_accounting(prefill: Sequence[Any],
                          decode: Sequence[Any]) -> Dict[str, int]:
    """Sum the tiers' transfer counters (local objects or actors) —
    the record's no-full-copy evidence."""
    from ray_tpu.serve.disagg import _call

    out = {"transfers": 0, "published_transfers": 0,
           "published_bytes": 0, "fetched_bytes": 0,
           "shm_bytes": 0, "rpc_bytes": 0}
    for p in prefill:
        s = _call(p, "stats")
        out["published_transfers"] += int(s.get("published_transfers", 0))
        out["published_bytes"] += int(s.get("published_bytes", 0))
    for d in decode:
        s = _call(d, "stats")
        out["transfers"] += int(s.get("transfers", 0))
        out["fetched_bytes"] += int(s.get("kv_fetched_bytes", 0))
        out["shm_bytes"] += int(s.get("shm_bytes", 0))
        out["rpc_bytes"] += int(s.get("rpc_bytes", 0))
    return out


def _tier_factories(params, config, args, use_cluster: bool,
                    chaos_spec: Optional[str] = None):
    """(prefill_factory, decode_factory, kill) — one replica per call,
    in-process objects or actors. The autoscaled run grows tiers through
    exactly these, so a scaled-up replica pays the same real cold-start
    (engine init + first compile) a production scale-up would.
    `chaos_spec` scripts kill_replica faults into the replicas; each
    factory numbers its replicas per role (creation index) so the plan
    targets exactly one, and a self-healer replacement (a later index)
    never re-fires the same action."""
    import itertools as it

    from ray_tpu.serve.disagg import DecodeServer, PrefillServer

    # retention must cover every transfer that can be legitimately
    # in flight (held from publish until the router acks after decode):
    # decode_replicas * (capacity + queue depth), and affinity can
    # route ALL of them to ONE prefill server — a smaller window would
    # reap chunks a decode replica is about to fetch, failing requests
    # under exactly the burst load the harness measures. The router
    # re-pushes the live bound on every add_*, this only seeds it.
    retain = max(32, 2 * args.decode_replicas
                 * (args.max_batch + args.queue_depth))
    pf_seq, dec_seq = it.count(), it.count()
    speculate_k = int(getattr(args, "_speculate_k", 0) or 0)
    kv_int8 = bool(getattr(args, "_kv_int8", False))
    # multi-tenant LoRA tiers (--tenants): cluster replicas page
    # adapters from the weight fabric (lora=True -> subscriber-backed
    # source; the driver publishes the tenant set up front), inline
    # replicas from a local source seeded with the same adapters
    lora_kw: Dict[str, Any] = {}
    tenant_adapters = getattr(args, "_tenant_adapters", None)
    if tenant_adapters:
        lora_kw = dict(
            lora=True if use_cluster else dict(tenant_adapters),
            lora_pool_slots=args.lora_pool_slots,
            lora_rank_max=max(args.lora_rank, 1))
    # --pool-blocks unset (None) flows through to resolve_pool_config's
    # own sizing — which is what doubles the defaulted pool under int8.
    # The harness must NOT double anything itself: an explicit size is
    # honored as-is (a user pinned it to fit HBM), and the int8
    # capacity gain in the record has to come from the real mechanism.
    kw = dict(kv_block_size=args.block_size,
              kv_pool_blocks=args.pool_blocks, kv_int8=kv_int8,
              retain=retain, chaos=chaos_spec, **lora_kw)
    # --kvplane legs pin the tiered KV plane on/off per run (None =
    # leave the replica on its env-knob default); the arena bound makes
    # the tier-2 spill capacity an explicit part of the record
    kvplane = getattr(args, "_kvplane", None)
    if kvplane is not None:
        kw["kvplane"] = bool(kvplane)
        if kvplane and getattr(args, "kvplane_arena_mb", 0):
            kw["kvplane_arena_bytes"] = int(
                args.kvplane_arena_mb) * (1 << 20)
    if use_cluster:
        import ray_tpu

        def prefill_factory():
            a = ray_tpu.remote(PrefillServer).options(
                max_concurrency=8).remote(
                    params, config, chaos_replica=next(pf_seq), **kw)
            ray_tpu.get(a.stats.remote(), timeout=120.0)  # fail fast
            return a

        def decode_factory():
            a = ray_tpu.remote(DecodeServer).options(
                max_concurrency=args.max_batch + 4).remote(
                    params, config, max_batch=args.max_batch,
                    chaos=chaos_spec, chaos_replica=next(dec_seq),
                    speculate_k=speculate_k, **lora_kw)
            ray_tpu.get(a.stats.remote(), timeout=120.0)
            return a

        def kill(replica):
            try:
                ray_tpu.kill(replica)
            except Exception:  # noqa: BLE001 — already gone
                pass
    else:
        def prefill_factory():
            return PrefillServer(params, config,
                                 chaos_replica=next(pf_seq), **kw)

        def decode_factory():
            return DecodeServer(params, config,
                                max_batch=args.max_batch,
                                chaos=chaos_spec,
                                chaos_replica=next(dec_seq),
                                speculate_k=speculate_k, **lora_kw)

        def kill(replica):
            stop = getattr(replica, "stop", None)
            if callable(stop):
                try:
                    stop()
                except Exception:  # noqa: BLE001 — already stopped
                    pass

    return prefill_factory, decode_factory, kill


def _build_tiers(params, config, args, use_cluster: bool,
                 prefill_replicas: Optional[int] = None,
                 decode_replicas: Optional[int] = None):
    """(router, prefill_list, decode_list, cleanup) for one mode."""
    from ray_tpu.serve.disagg import DisaggRouter

    pf_n = (args.prefill_replicas if prefill_replicas is None
            else prefill_replicas)
    dec_n = (args.decode_replicas if decode_replicas is None
             else decode_replicas)
    prefill_factory, decode_factory, kill = _tier_factories(
        params, config, args, use_cluster)
    prefill = [prefill_factory() for _ in range(pf_n)]
    decode = [decode_factory() for _ in range(dec_n)]
    router = DisaggRouter(decode=decode, prefill=prefill,
                          max_queue_depth=args.queue_depth,
                          affinity_tokens=args.block_size)

    def cleanup():
        # the ROUTER's live view, not the construction-time lists: an
        # autoscaled run may have grown or drained either tier
        live = [r["target"] for t in ("prefill", "decode")
                for r in router.tier_replicas(t)]
        for a in live:
            kill(a)

    return router, prefill, decode, cleanup


def _warm(router, prompts) -> None:
    """Warm the compile caches off the clock: each distinct prompt
    shape costs one prefill compile on first sight."""
    for p in prompts:
        router.generate(p, 2)


def _static_run(params, config, args, use_cluster, prompts, load_kw,
                pf_n: int, dec_n: int) -> Dict[str, Any]:
    """One fixed-(P,D) provisioning replayed through the open-loop
    schedule; replica-hours are simply (P + D) x wall."""
    router, prefill, decode, cleanup = _build_tiers(
        params, config, args, use_cluster, prefill_replicas=pf_n,
        decode_replicas=dec_n)
    try:
        _warm(router, prompts)
        warm_rt = router.stats()  # counters cover ONLY the measured run
        rec = run_load(router, prompts, **load_kw)
        st = router.stats()
        rec["router"] = {k: st[k] - warm_rt[k] for k in
                         ("dispatched", "completed", "shed")}
        rec["router"]["max_pending"] = st["max_pending"]
    finally:
        cleanup()
    rec["config"] = f"{pf_n}x{dec_n}"
    rec["prefill_replicas"] = pf_n
    rec["decode_replicas"] = dec_n
    rec["replica_hours"] = round(
        (pf_n + dec_n) * rec["wall_s"] / 3600.0, 6)
    return rec


def _autoscaled_run(params, config, args, use_cluster, prompts,
                    load_kw, target_p99_ms: float) -> Dict[str, Any]:
    """The closed control loop under the same schedule: tiers start at
    the minimum, the serve/autoscale.py policy drives them, and
    replica-hours are the loop's measured integral of live replicas."""
    from ray_tpu.serve.autoscale import (DisaggAutoscaler, DisaggPolicy,
                                         TierSpec)

    prefill_factory, decode_factory, _kill = _tier_factories(
        params, config, args, use_cluster)
    router, prefill, decode, cleanup = _build_tiers(
        params, config, args, use_cluster,
        prefill_replicas=args.min_prefill,
        decode_replicas=args.min_decode)
    scaler = DisaggAutoscaler(
        router,
        prefill=TierSpec(prefill_factory,
                         min_replicas=args.min_prefill,
                         max_replicas=args.max_prefill,
                         up_delay_s=args.up_delay,
                         down_delay_s=args.down_delay,
                         cooldown_s=args.cooldown),
        decode=TierSpec(decode_factory,
                        min_replicas=args.min_decode,
                        max_replicas=args.max_decode,
                        up_delay_s=args.up_delay,
                        down_delay_s=args.down_delay,
                        cooldown_s=args.cooldown),
        interval_s=args.autoscale_interval,
        drain_grace_s=args.drain_grace)
    scaler.policy.target_p99_ms = target_p99_ms
    try:
        _warm(router, prompts)
        warm_rt = router.stats()  # counters cover ONLY the measured run
        # the warm phase's first-compile TTFTs must not read as an SLO
        # breach when the policy wakes up
        router.reset_signal_windows()
        scaler.start()
        rec = run_load(router, prompts, **load_kw)
        st = router.stats()
        rec["router"] = {k: st[k] - warm_rt[k] for k in
                         ("dispatched", "completed", "shed")}
        rec["router"]["max_pending"] = st["max_pending"]
    finally:
        scaler.stop()
        cleanup()
    st = scaler.status()
    rs = st["replica_seconds"]
    rec["config"] = "autoscale"
    rec["replica_hours"] = round(
        (rs["prefill"] + rs["decode"]) / 3600.0, 6)
    rec["autoscale"] = {
        "target_p99_ms": target_p99_ms,
        "bounds": {"prefill": st["prefill_bounds"],
                   "decode": st["decode_bounds"]},
        "scale_ups": st["scale_ups"],
        "scale_downs": st["scale_downs"],
        "drains_completed": st["drains_completed"],
        "drains_forced": st["drains_forced"],
        "replica_seconds": rs,
        "final_active": {"prefill": st["prefill_active"],
                         "decode": st["decode_active"]},
    }
    return rec


def _fault_run(params, config, args, prompts, load_kw,
               chaos_spec: Optional[str]):
    """One open-loop run with tier self-healing attached (actor
    replicas over the real chunk fabric): the chaos harness's unit of
    measurement. Returns (record, outputs-by-request-index). The
    self-healer WATCHES (event-driven death handling) without the
    scaling tick — recovery here is pure failover + replacement, never
    a load decision."""
    from ray_tpu.serve.autoscale import DisaggAutoscaler, TierSpec
    from ray_tpu.serve.disagg import DisaggRouter, _call

    pf_n = args.prefill_replicas
    dec_n = max(2, args.decode_replicas)  # failover needs a survivor
    prefill_factory, decode_factory, kill = _tier_factories(
        params, config, args, True, chaos_spec)
    prefill = [prefill_factory() for _ in range(pf_n)]
    decode = [decode_factory() for _ in range(dec_n)]
    router = DisaggRouter(decode=decode, prefill=prefill,
                          max_queue_depth=args.queue_depth,
                          affinity_tokens=args.block_size)
    # bounds sized so a replacement always fits; the huge delays make
    # the hysteresis machinery inert even if someone calls tick()
    scaler = DisaggAutoscaler(
        router,
        prefill=TierSpec(prefill_factory, min_replicas=pf_n,
                         max_replicas=pf_n + 1, up_delay_s=3600.0,
                         down_delay_s=3600.0),
        decode=TierSpec(decode_factory, min_replicas=dec_n,
                        max_replicas=dec_n + 1, up_delay_s=3600.0,
                        down_delay_s=3600.0),
        interval_s=3600.0, drain_grace_s=args.drain_grace)
    outputs: Dict[int, List[int]] = {}
    try:
        _warm(router, prompts)
        # measurement starts HERE: zero the chaos counters so a plan's
        # `at=request:N` / `at=token:K` means the Nth MEASURED request
        # (Kth measured token), not warm-up traffic (PR-12 known limit)
        for tier in ("prefill", "decode"):
            for r in router.tier_replicas(tier):
                try:
                    _call(r["target"], "reset_chaos_counts")  # shardlint: disable=unsupervised-actor-call
                except Exception:  # noqa: BLE001 — pre-reset replica
                    pass
        warm_rt = router.stats()
        router.reset_signal_windows()
        scaler.watch()
        rec = run_load(router, prompts, outputs=outputs, **load_kw)
        st = router.stats()
        rec["router"] = {k: st[k] - warm_rt[k] for k in
                         ("dispatched", "completed", "shed")}
        rec["router"]["max_pending"] = st["max_pending"]
        # give the event-driven heal a moment to finish registering a
        # replacement before the teardown sweeps the replica set
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            h = scaler.servefault_stats()
            if sum(h["deaths"].values()) == \
                    sum(h["replacements"].values()) \
                    + h["replacements_blocked"]:
                break
            time.sleep(0.25)
        rec["servefault"] = router.servefault_stats()
        rec["healer"] = scaler.servefault_stats()
        router.publish_servefault(force=True)
    finally:
        scaler.stop()
        for t in ("prefill", "decode"):
            for r in router.tier_replicas(t):
                kill(r["target"])
    return rec, outputs


def _chaos_record(params, config, args, prompts, load_kw
                  ) -> Dict[str, Any]:
    """The acceptance scenario: a clean replay vs the same replay with
    a scripted replica kill. Records the failover recovery impact and
    the zero-silently-dropped / bit-identical verdict."""
    # the decode pick's free-slot tie-break favors the LAST replica,
    # so that's the one whose token counter reliably reaches the kill
    # point; prefill affinity hashes, so replica 0 is as good as any
    victim = (max(2, args.decode_replicas) - 1
              if args.chaos_role == "decode" else 0)
    plan = [{"action": "kill_replica", "role": args.chaos_role,
             "at": args.chaos_at, "replica": victim}]
    spec = json.dumps(plan)
    clean, clean_out = _fault_run(params, config, args, prompts,
                                  load_kw, None)
    chaos, chaos_out = _fault_run(params, config, args, prompts,
                                  load_kw, spec)
    common = sorted(set(clean_out) & set(chaos_out))
    mismatched = [i for i in common if clean_out[i] != chaos_out[i]]
    n = load_kw["n_requests"]
    sf = chaos.get("servefault") or {}
    healer = chaos.get("healer") or {}
    deaths = sum((healer.get("deaths") or {}).values())
    causes = chaos.get("shed_causes") or {}
    verdict = {
        # every accepted request either completed or shed WITH a cause
        "zero_silently_dropped": (not chaos.get("hung")
                                  and chaos.get("errors", 0) == 0
                                  and chaos["completed"]
                                  + chaos["shed"] == n),
        # falsifiable: run_load buckets cause-less sheds under
        # "unattributed" instead of defaulting them to a real cause
        "all_sheds_attributed": ("unattributed" not in causes
                                 and sum(causes.values())
                                 == chaos["shed"]),
        # failed-over requests match the clean run token-for-token
        "bit_identical_completed": not mismatched,
        "compared_outputs": len(common),
        "mismatched_outputs": mismatched[:8],
        "kill_fired": deaths >= 1,
        "failovers": sum((sf.get("failovers") or {}).values()),
        "replaced": sum((healer.get("replacements") or {}).values()),
    }
    verdict["pass"] = bool(
        verdict["zero_silently_dropped"]
        and verdict["all_sheds_attributed"]
        and verdict["bit_identical_completed"]
        and verdict["kill_fired"])
    recovery = {
        "ttft_p99_ms_clean": clean.get("ttft_p99_ms"),
        "ttft_p99_ms_chaos": chaos.get("ttft_p99_ms"),
        "latency_p99_ms_clean": clean.get("latency_p99_ms"),
        "latency_p99_ms_chaos": chaos.get("latency_p99_ms"),
        "failover_recovery_ms":
            sf.get("recent_failover_recovery_ms"),
    }
    return {"chaos_plan": plan, "clean": clean, "chaos": chaos,
            "recovery": recovery, "verdict": verdict}


# --------------------------------------------------------- HTTP front door


def _http_sse_drain(resp, t0: float) -> Dict[str, Any]:
    """Drain one SSE completions stream off the real socket: returns
    {text, ttft_s, finish, frames}. The concatenated deltas ARE the
    response — the bit-identity check compares them against the
    engine oracle verbatim."""
    text = ""
    ttft: Optional[float] = None
    finish: Optional[str] = None
    frames = 0
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.strip()
        if not line.startswith(b"data: "):
            continue
        payload = line[len(b"data: "):]
        if payload == b"[DONE]":
            break
        obj = json.loads(payload)
        if "error" in obj:
            raise RuntimeError(str(obj["error"].get("message",
                                                    "stream error")))
        frames += 1
        choice = obj["choices"][0]
        delta = choice.get("text") or ""
        if delta and ttft is None:
            ttft = time.perf_counter() - t0
        text += delta
        if choice.get("finish_reason"):
            finish = choice["finish_reason"]
    return {"text": text, "ttft_s": ttft, "finish": finish,
            "frames": frames}


def _http_record(params, config, args, prompts) -> Dict[str, Any]:
    """The front-door acceptance run: a mixed interactive+batch storm
    over REAL sockets against serve/gateway.py.

    Shape: `--http-max-batch` long batch decodes grab every engine slot
    at t=0 (slow clients — `token_sleep_s` pacing rides the request
    body); surplus batch arrivals land on the full system and shed with
    an attributed cause; interactive requests arrive mid-decode and
    must PREEMPT a batch slot (cancel + replay-with-history) to hold
    their TTFT SLO. Every completed response — including the preempted-
    then-resumed batch streams — must be bit-identical to a serial
    engine-oracle decode of the same prompt, which is what makes the
    preemption path oracle-checked rather than best-effort."""
    import dataclasses
    import http.client

    import jax

    from ray_tpu.models.engine import ContinuousBatchingEngine
    from ray_tpu.models.llama import llama_init
    from ray_tpu.serve.disagg import DisaggRouter
    from ray_tpu.serve.gateway import GatewayServer
    from ray_tpu.serve.qos import QosGate

    # The preemption window is the ENGINE's production time for a batch
    # stream, so the batch budget needs headroom past tiny()'s 128-token
    # horizon (llama has no learned positions — same seed, same weights)
    cfg = dataclasses.replace(
        config, max_seq_len=max(config.max_seq_len,
                                args.http_batch_new + 2 * args.block_size
                                + 32))
    params = llama_init(cfg, jax.random.PRNGKey(args.seed))
    prompts = make_prompts(cfg, n_distinct=args.distinct,
                           block_size=args.block_size, seed=args.seed)

    engine = ContinuousBatchingEngine(params, cfg,
                                      max_batch=args.http_max_batch)
    router = DisaggRouter(colocated=engine, max_queue_depth=0)
    gw = GatewayServer(router, model="bench",
                       vocab_size=cfg.vocab_size,
                       qos=QosGate(router=router),
                       max_tokens_cap=args.http_batch_new)
    host, port = gw.ready()

    n_fill = args.http_max_batch
    n_extra = max(0, args.http_batch - n_fill)
    n_inter = args.http_interactive
    rng = np.random.default_rng(args.seed)
    pop = 1.0 / np.arange(1, len(prompts) + 1) ** args.zipf_a
    picks = rng.choice(len(prompts), size=n_fill + n_extra + n_inter,
                       p=pop / pop.sum())

    # serial engine oracle BEFORE the storm: one uninterrupted greedy
    # decode per (prompt, budget) — doubles as compile warm-up, so the
    # measured TTFTs are steady-state
    oracle: Dict[Any, str] = {}
    for i in range(n_fill + n_extra + n_inter):
        budget = (args.http_interactive_new if i >= n_fill + n_extra
                  else args.http_batch_new)
        key = (int(picks[i]), budget)
        if key not in oracle:
            toks = engine.generate(prompts[int(picks[i])], budget)
            oracle[key] = " ".join(str(int(t)) for t in toks)

    plan: List[Dict[str, Any]] = []
    for i in range(n_fill):
        plan.append({"i": i, "cls": "batch", "offset": 0.0,
                     "budget": args.http_batch_new,
                     "pace": args.token_sleep})
    for i in range(n_extra):
        plan.append({"i": n_fill + i, "cls": "batch",
                     "offset": 0.4 + 0.05 * i,
                     "budget": args.http_batch_new, "pace": 0.0})
    for i in range(n_inter):
        plan.append({"i": n_fill + n_extra + i, "cls": "interactive",
                     "offset": 0.9 + 0.7 * i,
                     "budget": args.http_interactive_new, "pace": 0.0})

    lock = threading.Lock()
    results: List[Dict[str, Any]] = []

    def one(req: Dict[str, Any]) -> None:
        time.sleep(req["offset"])
        pidx = int(picks[req["i"]])
        body = json.dumps({
            "model": "bench", "prompt": prompts[pidx],
            "max_tokens": req["budget"], "stream": True,
            "priority": req["cls"],
            "token_sleep_s": req["pace"]})
        t0 = time.perf_counter()
        rec: Dict[str, Any] = {"i": req["i"], "class": req["cls"],
                               "prompt": pidx, "budget": req["budget"]}
        try:
            conn = http.client.HTTPConnection(host, port, timeout=180)
            conn.request("POST", "/v1/completions", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            rec["status"] = resp.status
            if resp.status == 200:
                out = _http_sse_drain(resp, t0)
                rec["outcome"] = "ok"
                rec["text"] = out["text"]
                rec["ttft_ms"] = (round(out["ttft_s"] * 1e3, 2)
                                  if out["ttft_s"] is not None else None)
                rec["finish"] = out["finish"]
            else:
                rec["outcome"] = "shed" if resp.status in (429, 503) \
                    else "error"
                rec["cause"] = (resp.headers.get("X-Shed-Cause")
                                or "unattributed")
                try:
                    err = json.loads(resp.read() or b"{}")
                    if rec["cause"] == "unattributed":
                        rec["cause"] = err.get("error", {}).get(
                            "code") or "unattributed"
                except Exception:  # noqa: BLE001 — cause is best-effort
                    pass
            conn.close()
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            rec["outcome"] = "error"
            rec["cause"] = f"{type(e).__name__}: {str(e)[:120]}"
        rec["latency_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        with lock:
            results.append(rec)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=one, args=(r,), daemon=True)
               for r in plan]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=240)
    wall = time.perf_counter() - t_start

    router.publish_telemetry(force=True)
    gw.publish_telemetry(force=True)
    rt = router.stats()
    kv = engine.kv_stats()
    gw_stats = gw.stats()
    gw.stop()
    engine.stop()

    with lock:
        rows = list(results)
    by_class: Dict[str, Dict[str, Any]] = {}
    mismatches: List[Dict[str, Any]] = []
    for cls in ("interactive", "batch"):
        sub = [r for r in rows if r["class"] == cls]
        ttfts = sorted(r["ttft_ms"] for r in sub
                       if r.get("ttft_ms") is not None)
        lats = sorted(r["latency_ms"] for r in sub)
        pct = (lambda xs, p: round(float(np.percentile(xs, p)), 2)
               if xs else None)
        by_class[cls] = {
            "n": len(sub),
            "completed": sum(1 for r in sub if r.get("outcome") == "ok"),
            "shed": sum(1 for r in sub if r.get("outcome") == "shed"),
            "errors": sum(1 for r in sub
                          if r.get("outcome") == "error"),
            "shed_causes": {},
            "ttft_p50_ms": pct(ttfts, 50),
            "ttft_p99_ms": pct(ttfts, 99),
            "latency_p50_ms": pct(lats, 50),
            "latency_p99_ms": pct(lats, 99),
        }
        for r in sub:
            if r.get("outcome") == "shed":
                c = r.get("cause") or "unattributed"
                sc = by_class[cls]["shed_causes"]
                sc[c] = sc.get(c, 0) + 1
    for r in rows:
        if r.get("outcome") != "ok":
            continue
        want = oracle[(r["prompt"], r["budget"])]
        if r["text"] != want:
            mismatches.append({"i": r["i"], "class": r["class"],
                               "prompt": r["prompt"],
                               "got_len": len(r["text"]),
                               "want_len": len(want)})

    inter, batch = by_class["interactive"], by_class["batch"]
    total = len(rows)
    verdict: Dict[str, Any] = {
        "accounted": (sum(c["n"] for c in by_class.values())
                      == len(plan) == total),
        "bit_identity": not mismatches,
        "interactive_ttft_slo_ms": args.http_slo_ms,
        "interactive_ttft_slo": (
            inter["ttft_p99_ms"] is not None
            and inter["ttft_p99_ms"] <= args.http_slo_ms),
        "interactive_all_served": (
            inter["completed"] == inter["n"] and inter["shed"] == 0),
        "batch_absorbs": (
            batch["shed"] >= (1 if n_extra else 0)
            and "unattributed" not in batch["shed_causes"]
            and inter["shed"] == 0),
        "preemptions_observed": int(rt.get("preemptions", 0)) >= 1,
        "preempted_resumed": int(rt.get("preempted_requests", 0)) >= 1,
        "no_errors": all(c["errors"] == 0 for c in by_class.values()),
    }
    verdict["pass"] = all(
        verdict[k] for k in ("accounted", "bit_identity",
                             "interactive_ttft_slo",
                             "interactive_all_served", "batch_absorbs",
                             "preemptions_observed", "preempted_resumed",
                             "no_errors"))
    rec: Dict[str, Any] = {
        "n_requests": total,
        "wall_s": round(wall, 3),
        "by_class": by_class,
        "preemptions": int(rt.get("preemptions", 0)),
        "preempted_requests": int(rt.get("preempted_requests", 0)),
        "router_sheds_by_cause": dict(rt.get("sheds_by_cause") or {}),
        "engine_cancels_by_reason": dict(
            kv.get("cancelled_by_reason") or {}),
        "gateway": {k: gw_stats.get(k) for k in
                    ("accepted", "completed", "streamed", "tokens_out",
                     "rate_limited", "sheds", "disconnects", "errors",
                     "by_class", "by_code", "ttft_ms")},
        "requests": [{k: v for k, v in r.items() if k != "text"}
                     for r in sorted(rows, key=lambda r: r["i"])],
        "verdict": verdict,
    }
    if mismatches:
        rec["mismatches"] = mismatches[:5]
    return rec


def _collect_lora_pools(router) -> Dict[str, int]:
    """Sum the tier replicas' adapter-pool counters (local objects or
    actors) — the record's paging-amortization evidence."""
    from ray_tpu.serve.disagg import _call

    out = {k: 0 for k in ("acquires", "hits", "misses", "evictions",
                          "swaps", "page_in_bytes", "resident")}
    for tier in ("prefill", "decode"):
        for r in router.tier_replicas(tier):
            s = _call(r["target"], "stats").get("lora") or {}  # shardlint: disable=unsupervised-actor-call
            for k in out:
                out[k] += int(s.get(k, 0))
    return out


def _lora_record(params, config, args, prompts, load_kw,
                 use_cluster: bool) -> Dict[str, Any]:
    """The multi-tenant LoRA acceptance run (``--tenants N``): tenants
    drawn Zipf over N adapters against pools holding fewer, one
    mid-run adapter publish for the hottest tenant, and the four
    verdicts the ROADMAP item names — paging amortized (hit rate high,
    page-in bytes « per-request adapter bytes), per-tenant isolation
    of shed/SLO counters, mixed-batch outputs bit-identical to
    sequential per-tenant runs, and untouched tenants' TTFT flat
    across the publish."""
    from ray_tpu.serve.disagg import _call
    from ray_tpu.serve.lora import (adapter_nbytes, make_lora_adapter,
                                    publish_adapter)

    tenants = [f"t{i:03d}" for i in range(args.tenants)]
    adapters = {t: make_lora_adapter(config, args.lora_rank,
                                     seed=1000 + i)
                for i, t in enumerate(tenants)}
    warm_tenant = "warmup"  # compiles the lora programs off the clock
    adapters[warm_tenant] = make_lora_adapter(config, args.lora_rank,
                                              seed=9999)
    args._tenant_adapters = adapters
    if use_cluster:
        # the fabric is the paging source: publish the tenant set up
        # front, replicas fetch on demand (real page-in byte
        # accounting through the subscriber)
        for t, a in adapters.items():
            publish_adapter(t, a)
    router, prefill, decode, cleanup = _build_tiers(
        params, config, args, use_cluster)
    pub_tenant = tenants[0]  # Zipf rank 1: the hottest tenant
    try:
        for p in prompts:
            router.generate(p, 2)
            router.generate(p, 2, tenant=warm_tenant)
        warm_rt = router.stats()
        warm_pools = _collect_lora_pools(router)
        router.reset_signal_windows()
        samples: List[Dict[str, Any]] = []
        outputs: Dict[int, List[int]] = {}
        publish_at_s = 0.5 * load_kw["n_requests"] / load_kw["rate_rps"]
        pub_state: Dict[str, Any] = {}

        def publisher():
            time.sleep(publish_at_s)
            v2 = make_lora_adapter(config, args.lora_rank, seed=7777)
            t0 = time.perf_counter()
            try:
                if use_cluster:
                    pub_state["version"] = publish_adapter(pub_tenant,
                                                           v2)
                else:
                    for tier in ("prefill", "decode"):
                        for r in router.tier_replicas(tier):
                            pub_state["version"] = _call(  # shardlint: disable=unsupervised-actor-call
                                r["target"], "publish_adapter",
                                pub_tenant, v2)
                pub_state["publish_ms"] = (time.perf_counter() - t0) \
                    * 1e3
            except Exception as e:  # noqa: BLE001 — recorded
                pub_state["error"] = f"{type(e).__name__}: {e}"

        th = threading.Thread(target=publisher, daemon=True)
        th.start()
        rec = run_load(router, prompts, tenants=tenants,
                       tenant_zipf=args.tenant_zipf, samples=samples,
                       outputs=outputs, **load_kw)
        th.join(timeout=30.0)
        st = router.stats()
        rec["router"] = {k: st[k] - warm_rt[k] for k in
                         ("dispatched", "completed", "shed")}
        rec["router"]["max_pending"] = st["max_pending"]
        pools_end = _collect_lora_pools(router)
        pools = {k: pools_end[k] - warm_pools.get(k, 0)
                 for k in pools_end if k != "resident"}
        pools["resident"] = pools_end["resident"]
        acq = pools["acquires"]
        hit_rate = pools["hits"] / acq if acq else 0.0
        # paging-amortization denominator: the bytes a pool-less
        # design would move — every tenant-tagged request ships its
        # whole adapter to both tiers
        naive = 2 * sum(adapter_nbytes(adapters[s["tenant"]])
                        for s in samples if s.get("tenant"))
        # per-tenant isolation: the router's counters, straight off
        # the lora surface
        tstats = router.tenant_stats()
        tstats.pop(warm_tenant, None)
        per_tenant = {t: {k: v[k] for k in ("dispatched", "completed",
                                            "shed", "slo_misses")}
                      for t, v in tstats.items()}
        isolation_ok = all(
            v["completed"] <= v["dispatched"]
            for v in per_tenant.values()) and sum(
            v["dispatched"] for v in per_tenant.values()) == \
            rec["router"]["dispatched"]
        # mixed-batch bit-identity: re-run a sample of completed
        # requests SEQUENTIALLY (one at a time, same tenant + prompt)
        # and diff — greedy decode must not care about batch
        # composition. The hot-published tenant is excluded (its
        # adapter changed mid-run by design). The prefix caches are
        # flushed first so the re-runs prefill CACHE-COLD: the check
        # then independently covers the prefill path instead of
        # replaying whatever the mixed run cached.
        for r in router.tier_replicas("prefill"):
            try:
                _call(r["target"], "invalidate_prefix_cache")  # shardlint: disable=unsupervised-actor-call
            except Exception:  # noqa: BLE001 — older replica
                pass
        checked = mismatched = 0
        for s in samples:
            if checked >= 12:
                break
            if s["tenant"] == pub_tenant or s["i"] not in outputs:
                continue
            seq = router.generate(prompts[s["prompt"]],
                                  load_kw["max_new_tokens"],
                                  tenant=s["tenant"])
            checked += 1
            if list(seq) != outputs[s["i"]]:
                mismatched += 1
        # publish-no-stall: untouched tenants' TTFT before vs after
        # the publish instant
        untouched = [s for s in samples
                     if s["tenant"] not in (pub_tenant, None)
                     and s["ttft_ms"] is not None]
        before = sorted(s["ttft_ms"] for s in untouched
                        if s["offset_s"] < publish_at_s)
        after = sorted(s["ttft_ms"] for s in untouched
                       if s["offset_s"] >= publish_at_s)
        p99 = (lambda xs: round(float(np.percentile(xs, 99)), 2)
               if xs else None)
        p99_before, p99_after = p99(before), p99(after)
        ttft_flat = (p99_before is not None and p99_after is not None
                     and p99_after <= max(2.5 * p99_before,
                                          p99_before + 250.0))
        rec["lora"] = {
            "tenants": len(tenants),
            "tenant_zipf": args.tenant_zipf,
            "pool_slots": args.lora_pool_slots,
            "rank": args.lora_rank,
            "adapter_nbytes": adapter_nbytes(adapters[pub_tenant]),
            "pools": pools,
            "hit_rate": round(hit_rate, 4),
            "page_in_bytes": pools["page_in_bytes"],
            "naive_per_request_adapter_bytes": naive,
            "paging_ratio": round(pools["page_in_bytes"] / naive, 4)
            if naive else None,
            "per_tenant": per_tenant,
            "publish": {
                "tenant": pub_tenant, "at_s": publish_at_s,
                **pub_state,
                "untouched_ttft_p99_before_ms": p99_before,
                "untouched_ttft_p99_after_ms": p99_after,
            },
            "bit_identity": {"checked": checked,
                             "mismatched": mismatched},
        }
        rec["lora"]["verdict"] = {
            "paging_amortized": (hit_rate >= 0.5
                                 and naive > 0
                                 and pools["page_in_bytes"] < naive),
            "tenant_isolation": isolation_ok,
            "mixed_batch_bit_identical": (checked > 0
                                          and mismatched == 0),
            "publish_no_stall": ttft_flat and "error" not in pub_state,
        }
        rec["lora"]["verdict"]["pass"] = all(
            rec["lora"]["verdict"].values())
        for tier_reps in (prefill, decode):
            for rep in tier_reps:
                try:
                    _call(rep, "publish_telemetry", True)  # shardlint: disable=unsupervised-actor-call
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
    finally:
        cleanup()
    return rec


def _spec_run(params, config, args, prompts, load_kw, use_cluster,
              speculate_k: int, kv_int8: bool):
    """One mode of the speculative-decoding comparison: build tiers
    with the given knobs, replay the SAME open-loop Zipf schedule, and
    return (record, per-request outputs). The transient `_speculate_k`
    / `_kv_int8` attrs parameterize `_tier_factories` without touching
    the user-visible flags (each mode overrides them)."""
    from ray_tpu.serve.disagg import _call

    args._speculate_k = speculate_k
    args._kv_int8 = kv_int8
    router, prefill, decode, cleanup = _build_tiers(
        params, config, args, use_cluster)
    try:
        _warm(router, prompts)
        if speculate_k:
            # the verify program (q = k+1) compiles on the first tick
            # that actually holds a draft — the repeat pass hits the
            # output memory, drafts, and pays that compile OFF the
            # measured clock (the plain _warm's 2-token budget never
            # drafts)
            for p in prompts[:2]:
                router.generate(p, 12)
                router.generate(p, 12)
        outputs: Dict[int, List[int]] = {}
        rec = run_load(router, prompts, outputs=outputs, **load_kw)
        # decode-tier speculation counters (acceptance, tokens/verify)
        spec = {"speculate_k": speculate_k, "spec_proposed": 0,
                "spec_accepted": 0, "spec_verify_ticks": 0,
                "spec_emitted_tokens": 0}
        for d in decode:
            s = _call(d, "stats").get("speculation") or {}
            for k in ("spec_proposed", "spec_accepted",
                      "spec_verify_ticks", "spec_emitted_tokens"):
                spec[k] += int(s.get(k, 0))
        if spec["spec_proposed"]:
            spec["acceptance_rate"] = round(
                spec["spec_accepted"] / spec["spec_proposed"], 4)
        if spec["spec_verify_ticks"]:
            spec["tokens_per_verify"] = round(
                spec["spec_emitted_tokens"] / spec["spec_verify_ticks"],
                3)
        rec["speculation"] = spec
        # prefill-tier pool capacity (the int8-doubling evidence)
        pool = {"effective_pool_blocks": 0, "capacity_factor": 1,
                "int8": kv_int8}
        for p in prefill:
            pc = _call(p, "stats").get("prefix_cache") or {}
            pool["effective_pool_blocks"] += int(pc.get("num_blocks", 0))
            pool["capacity_factor"] = max(pool["capacity_factor"],
                                          int(pc.get("capacity_factor",
                                                     1)))
        rec["kv_pool"] = pool
    finally:
        cleanup()
        args._speculate_k = 0
        args._kv_int8 = False
    return rec, outputs


def _int8_logit_probe(params, config, args,
                      prompts) -> Dict[str, Any]:
    """The int8 tolerance contract, measured directly: prefill the
    hottest prompt once from scratch (exact KV) and once through a hit
    on an int8 pool (quantize-on-commit -> dequant-on-gather), and
    compare the last-position logits. Token streams are ints, so
    'unchanged within rtol' is a statement about THESE — quantization
    may legitimately flip a near-tie greedy argmax, and the probe
    bounds how near the tie has to be."""
    import jax.numpy as jnp

    from ray_tpu.models.engine import _prefill_paged
    from ray_tpu.models.generate import _model_fns
    from ray_tpu.models.kvcache import PagedKVCache

    prompt = np.asarray(prompts[0], np.int32)[None]
    probe = _model_fns(config)[1](config, 1, max_len=1)
    empty = jnp.zeros((len(probe), 0) + probe[0]["k"].shape[2:],
                      probe[0]["k"].dtype)
    ref_logits, ck, cv = _prefill_paged(params, prompt, config, empty,
                                        empty)
    kv = PagedKVCache(config, block_size=args.block_size,
                      num_blocks=max(args.pool_blocks or 32, 16),
                      int8=True)
    m = kv.lookup(prompt[0], max_tokens=prompt.shape[1] - 1)
    kv.commit(prompt[0], ck, cv, m)
    m2 = kv.lookup(prompt[0], max_tokens=prompt.shape[1] - 1)
    pk, pv = kv.gather(m2)
    q_logits, _, _ = _prefill_paged(params, prompt[:, m2.tokens:],
                                    config, pk, pv)
    ref = np.asarray(ref_logits[0, :config.vocab_size], np.float32)
    got = np.asarray(q_logits[0, :config.vocab_size], np.float32)
    rel = float(np.max(np.abs(got - ref))
                / (np.max(np.abs(ref)) + 1e-9))
    return {"reused_tokens": int(m2.tokens),
            "max_rel_err": round(rel, 5),
            "rtol_bound": 0.05,
            "within_rtol": rel <= 0.05}


def _outputs_identical(base: Dict[int, List[int]],
                       other: Dict[int, List[int]]) -> Dict[str, Any]:
    """Bit-identity evidence over the requests BOTH runs completed
    (sheds may differ between runs — admission timing is load-
    dependent — but any request served by both must match exactly)."""
    common = sorted(set(base) & set(other))
    mismatched = [i for i in common if base[i] != other[i]]
    return {"compared": len(common), "mismatched": len(mismatched),
            "identical": bool(common) and not mismatched}


def _spec_record(params, config, args, prompts, load_kw,
                 use_cluster) -> Dict[str, Any]:
    """The --speculate comparison: the SAME open-loop Zipf schedule
    replayed unspeculated (the PR-9-shaped baseline), speculated, and —
    with --kv-int8 — speculated over the int8 KV pool. The verdict
    gates on >= 2x tokens/s with bit-identical greedy outputs
    (speculation) and unchanged outputs over the quantized pool (int8;
    the pool's dequant rtol bound is tested in tests/test_speculate.py
    — token streams are ints, so "within rtol" at this level means
    unchanged)."""
    out: Dict[str, Any] = {}
    base_rec, base_out = _spec_run(params, config, args, prompts,
                                   load_kw, use_cluster, 0, False)
    out["baseline"] = base_rec
    spec_rec, spec_out = _spec_run(params, config, args, prompts,
                                   load_kw, use_cluster,
                                   args.speculate, False)
    spec_rec["vs_baseline"] = _outputs_identical(base_out, spec_out)
    out["speculate"] = spec_rec
    speedup = (spec_rec["tokens_per_sec"] / base_rec["tokens_per_sec"]
               if base_rec["tokens_per_sec"] else 0.0)
    verdict: Dict[str, Any] = {
        "speedup": round(speedup, 3),
        "bit_identical": spec_rec["vs_baseline"]["identical"],
        "acceptance_rate":
            spec_rec["speculation"].get("acceptance_rate", 0.0),
        "tokens_per_verify":
            spec_rec["speculation"].get("tokens_per_verify", 0.0),
    }
    int8_ok = True
    if args.kv_int8:
        int8_rec, int8_out = _spec_run(params, config, args, prompts,
                                       load_kw, use_cluster,
                                       args.speculate, True)
        int8_rec["vs_baseline"] = _outputs_identical(base_out, int8_out)
        int8_rec["logit_equivalence"] = _int8_logit_probe(
            params, config, args, prompts)
        out["int8"] = int8_rec
        verdict["int8_within_rtol"] = \
            int8_rec["logit_equivalence"]["within_rtol"]
        verdict["int8_output_match_rate"] = round(
            1.0 - int8_rec["vs_baseline"]["mismatched"]
            / max(1, int8_rec["vs_baseline"]["compared"]), 4)
        verdict["int8_pool_gain"] = round(
            int8_rec["kv_pool"]["effective_pool_blocks"]
            / max(1, base_rec["kv_pool"]["effective_pool_blocks"]), 3)
        int8_ok = (verdict["int8_within_rtol"]
                   and verdict["int8_pool_gain"] >= 2.0)
    verdict["pass"] = bool(
        speedup >= 2.0 and verdict["bit_identical"] and int8_ok)
    out["verdict"] = verdict
    return out


def _kvplane_prompts(config, *, n_distinct: int = 8,
                     block_size: int = 16, sys_blocks: int = 2,
                     tail_blocks: int = 4,
                     seed: int = 0) -> List[List[int]]:
    """make_prompts with DEEP distinct tails: each prompt carries
    `tail_blocks` full blocks of its own past the shared system prefix,
    so the distinct-block working set (sys_blocks + n_distinct *
    tail_blocks) can be sized past one replica's HBM pool — the
    pressure that makes the tiered plane's spill path load-bearing."""
    rng = np.random.default_rng(seed)
    sys_prompt = rng.integers(1, config.vocab_size,
                              sys_blocks * block_size).tolist()
    return [sys_prompt + rng.integers(
        1, config.vocab_size,
        tail_blocks * block_size + int(rng.integers(2, block_size))
        ).tolist() for _ in range(n_distinct)]


# per-replica kvplane counters the record aggregates (monotone only —
# gauges like arena entries/bytes don't survive warm-up subtraction)
_KVP_COUNTERS = (
    "spills", "spill_bytes", "tier2_hits", "tier2_probes",
    "tier2_reused_tokens", "tier2_fetched_bytes", "arena_evictions",
    "tier3_publishes", "tier3_adopts", "tier3_adopted_blocks",
    "tier3_reused_tokens", "tier3_fetched_bytes", "evict_storms",
    "storm_evicted_blocks")


def _kvp_totals(prefill_targets) -> Dict[str, int]:
    """Tier counters summed over EVERY prefill replica the leg ever
    created (a cold-swapped replica leaves the router but its spill
    and publish history still belongs to the run's accounting), plus
    the engine-level reused_tokens total (tier-1 hits AND arena
    re-adopts AND tier-3 imports all land there — it is the
    cross-leg comparable 'prefill work the caches absorbed')."""
    from ray_tpu.serve.disagg import _call

    tot = {k: 0 for k in _KVP_COUNTERS}
    tot["reused_tokens"] = 0
    for t in prefill_targets:
        try:
            kvp = _call(t, "kvplane_stats")  # shardlint: disable=unsupervised-actor-call
            st = _call(t, "stats")  # shardlint: disable=unsupervised-actor-call
        except Exception:  # noqa: BLE001 — replica mid-teardown
            continue
        for k in _KVP_COUNTERS:
            tot[k] += int(kvp.get(k, 0))
        tot["reused_tokens"] += int(st.get("reused_tokens", 0))
    return tot


def _kvplane_reset_directory() -> None:
    """Reap every prefix-directory entry between legs (TTL 0 reaps
    unconditionally): a later leg's lookups must not ride the previous
    leg's publishes — its holders are gone, and a stale fallback hint
    would smear tier-3 traffic across the per-leg attribution."""
    import ray_tpu

    w = ray_tpu._private.worker.global_worker
    if w is None or getattr(w, "conductor", None) is None:
        return
    try:
        w.conductor.call("kvplane_reap", 0.0, timeout=5.0)
    except Exception:  # noqa: BLE001 — best-effort hygiene
        pass


def _kvplane_run(params, config, args, prompts, load_kw, *,
                 kvplane: bool, chaos_spec: Optional[str] = None,
                 cold_swap: bool = False,
                 pool_blocks: Optional[int] = None):
    """One leg of the --kvplane comparison: replay the SAME open-loop
    Zipf schedule with the tiered plane pinned on or off (and, for the
    HBM-reference leg, `pool_blocks` overriding the deliberately small
    pool). Returns (record, per-request outputs). With `cold_swap`,
    after the measured run the entire prefill tier is RETIRED from the
    router (replicas stay alive so their published tier-3 chunks do)
    and replaced with cold replicas, then every distinct prompt
    replays once: the directory's holders are gone, so each lookup
    degrades to a fallback hint and the cold replica re-adopts the
    prefix from the object store — the tier-3 persistence story,
    measured."""
    from ray_tpu.serve.disagg import DisaggRouter, _call

    pf_n = max(2, args.prefill_replicas)
    dec_n = args.decode_replicas
    prev_pool = args.pool_blocks
    if pool_blocks is not None:
        args.pool_blocks = pool_blocks
    args._kvplane = kvplane
    try:
        prefill_factory, decode_factory, kill = _tier_factories(
            params, config, args, True, chaos_spec)
        prefill = [prefill_factory() for _ in range(pf_n)]
        decode = [decode_factory() for _ in range(dec_n)]
        all_prefill = list(prefill)
        router = DisaggRouter(decode=decode, prefill=prefill,
                              max_queue_depth=args.queue_depth,
                              affinity_tokens=args.block_size)
        outputs: Dict[int, List[int]] = {}
        try:
            _warm(router, prompts)
            # measurement starts HERE (chaos `at=request:N` counts
            # measured traffic only, counters subtract the warm-up)
            for r in router.tier_replicas("prefill"):
                try:
                    _call(r["target"], "reset_chaos_counts")  # shardlint: disable=unsupervised-actor-call
                except Exception:  # noqa: BLE001 — pre-reset replica
                    pass
            warm_rt = router.stats()
            warm_kvp = _kvp_totals(all_prefill)
            rec = run_load(router, prompts, outputs=outputs, **load_kw)
            st = router.stats()
            rec["router"] = {k: st[k] - warm_rt[k] for k in
                             ("dispatched", "completed", "shed",
                              "directory_hits", "directory_misses",
                              "directory_fallbacks")}
            rec["router"]["max_pending"] = st["max_pending"]
            # tier counters cover exactly the measured run — the cold
            # replay below is extra work the baseline leg never does,
            # so it gets its OWN deltas, not a seat in these
            run_kvp = _kvp_totals(all_prefill)
            rec["kvplane"] = {k: run_kvp[k] - warm_kvp[k]
                              for k in run_kvp}
            rec["kvplane"]["enabled"] = bool(kvplane)
            rec["kvplane"]["directory"] = router.kvplane_stats()
            if cold_swap:
                ref = [router.generate(p, args.max_new)
                       for p in prompts]
                pre = _kvp_totals(all_prefill)
                pre_rt = router.stats()
                for r in router.tier_replicas("prefill"):
                    router.remove_dead("prefill", r["rid"])
                fresh = [prefill_factory() for _ in range(pf_n)]
                for a in fresh:
                    router.add_prefill(a)
                all_prefill.extend(fresh)
                got = [router.generate(p, args.max_new)
                       for p in prompts]
                post = _kvp_totals(all_prefill)
                post_rt = router.stats()
                rec["cold_replay"] = {
                    "prompts": len(prompts),
                    "bit_identical": got == ref,
                    "directory_fallbacks":
                        post_rt["directory_fallbacks"]
                        - pre_rt["directory_fallbacks"],
                }
                for k in ("tier3_adopts", "tier3_adopted_blocks",
                          "tier3_reused_tokens", "tier3_fetched_bytes"):
                    rec["cold_replay"][k] = post[k] - pre[k]
            router.publish_telemetry(force=True)
        finally:
            for t in all_prefill:
                kill(t)
            for r in router.tier_replicas("decode"):
                kill(r["target"])
    finally:
        args._kvplane = None
        args.pool_blocks = prev_pool
    return rec, outputs


def _kvplane_record(params, config, args, prompts,
                    load_kw) -> Dict[str, Any]:
    """The --kvplane acceptance scenario: a Zipf replay whose distinct-
    block working set exceeds one replica's HBM pool, run four ways on
    the SAME schedule — (1) `hbm_reference`: the plane off and a pool
    big enough to never evict, the engine an unlimited-HBM replica
    would be; (2) `baseline`: the plane off and the SMALL pool —
    single-tier, evictions simply lose the prefix; (3) `kvplane`: the
    small pool with the plane on — spills land in the host arena and
    come back, the directory routes repeats to holders, and a
    cold-swapped prefill tier re-adopts everything from the object
    store; (4) `storm`: the plane on under a scripted evict_storm.

    All legs run int8 pools: the spill/publish wire format IS the int8
    pool block, so tier-2 re-adopts and tier-3 imports round-trip
    byte-exactly and every full prefix match — resident, re-adopted,
    or imported — gathers the same bytes at the same split as the
    reference's resident hit. That is what lets the verdict demand
    BIT-IDENTICAL outputs from the tiered legs against the reference
    (fp pools would quantize on spill: rtol-close, not bit-equal).
    The verdict gates on strictly more reused tokens than the
    single-tier baseline absorbed, tier-2 AND tier-3 actually
    engaging, bit-identical outputs vs the reference everywhere, and
    zero wrong outputs through the storm."""
    out: Dict[str, Any] = {}
    bs = args.block_size
    blocks = set()
    for p in prompts:
        for i in range(len(p) // bs):
            blocks.add(tuple(p[:(i + 1) * bs]))
    out["working_set_blocks"] = len(blocks)
    out["pool_blocks"] = args.pool_blocks
    ref_pool = len(blocks) + 16  # whole working set + pinning slack
    out["reference_pool_blocks"] = ref_pool

    ref_rec, ref_out = _kvplane_run(params, config, args, prompts,
                                    load_kw, kvplane=False,
                                    pool_blocks=ref_pool)
    out["hbm_reference"] = ref_rec
    _kvplane_reset_directory()
    base_rec, base_out = _kvplane_run(params, config, args, prompts,
                                      load_kw, kvplane=False)
    out["baseline"] = base_rec
    _kvplane_reset_directory()
    kv_rec, kv_out = _kvplane_run(params, config, args, prompts,
                                  load_kw, kvplane=True,
                                  cold_swap=True)
    kv_rec["vs_reference"] = _outputs_identical(ref_out, kv_out)
    out["kvplane"] = kv_rec
    _kvplane_reset_directory()
    # storm every replica's whole pool early in the measured run —
    # the arena must hand every evicted block straight back
    plan = json.dumps([
        {"action": "evict_storm", "role": "prefill",
         "blocks": max(int(args.pool_blocks or 1), 1),
         "at": "request:2", "replica": r}
        for r in range(max(2, args.prefill_replicas))])
    storm_rec, storm_out = _kvplane_run(params, config, args, prompts,
                                        load_kw, kvplane=True,
                                        chaos_spec=plan)
    storm_rec["vs_reference"] = _outputs_identical(ref_out, storm_out)
    out["storm"] = storm_rec

    kvp = kv_rec["kvplane"]
    cold = kv_rec.get("cold_replay") or {}
    rtr = kv_rec["router"]
    probes = (rtr["directory_hits"] + rtr["directory_misses"]
              + rtr["directory_fallbacks"])
    verdict = {
        "working_set_exceeds_pool":
            out["working_set_blocks"] > int(args.pool_blocks or 0),
        "pool_pressure": kvp["spills"] > 0,
        "baseline_reused_tokens":
            base_rec["kvplane"]["reused_tokens"],
        "kvplane_reused_tokens": kvp["reused_tokens"],
        "multi_tier_reuse_gain":
            kvp["reused_tokens"]
            > base_rec["kvplane"]["reused_tokens"],
        "tier2_reused_tokens": kvp["tier2_reused_tokens"],
        "tier3_reused_tokens": cold.get("tier3_reused_tokens", 0),
        "directory_hits": rtr["directory_hits"],
        "directory_hit_rate": (round(rtr["directory_hits"] / probes, 4)
                               if probes else 0.0),
        "bit_identical_vs_reference":
            kv_rec["vs_reference"]["identical"],
        "cold_replay_bit_identical": bool(cold.get("bit_identical")),
        "storm_fired": storm_rec["kvplane"]["evict_storms"] >= 1,
        "storm_zero_wrong":
            (storm_rec["vs_reference"]["compared"] > 0
             and storm_rec["vs_reference"]["mismatched"] == 0),
    }
    verdict["pass"] = bool(
        all(_clean_run(r) for r in (ref_rec, base_rec, kv_rec,
                                    storm_rec))
        and verdict["working_set_exceeds_pool"]
        and verdict["pool_pressure"]
        and verdict["multi_tier_reuse_gain"]
        and verdict["tier2_reused_tokens"] > 0
        and verdict["tier3_reused_tokens"] > 0
        and verdict["directory_hits"] > 0
        and verdict["bit_identical_vs_reference"]
        and verdict["cold_replay_bit_identical"]
        and verdict["storm_fired"]
        and verdict["storm_zero_wrong"])
    out["verdict"] = verdict
    return out


def _clean_run(rec: Dict[str, Any]) -> bool:
    """A run may headline/verdict only when every request is accounted
    ok|shed — a hung or errored request silently shrinking the measured
    population is exactly the lie the r04/r05 rule exists to prevent."""
    return not rec.get("hung") and not rec.get("errors")


def compare_verdict(auto: Dict[str, Any], sweep: List[Dict[str, Any]],
                    target_p99_ms: float) -> Dict[str, Any]:
    """The acceptance comparison: the autoscaled run beats a static
    (P,D) either because the static config misses the SLO (TTFT p99
    over target, or it sheds more at the peak than the autoscaled run
    did), or — when the static config does meet it — because the
    autoscaler matched the SLO with strictly fewer replica-hours. Shed
    discipline is additionally checked against the BEST static config
    (lowest p99). Any hung/errored run voids the verdict entirely."""
    valid = _clean_run(auto) and all(_clean_run(s) for s in sweep)
    auto_p99 = auto.get("ttft_p99_ms")
    auto_ok = auto_p99 is not None and auto_p99 <= target_p99_ms
    per = []
    for s in sweep:
        p99 = s.get("ttft_p99_ms")
        slo_ok = (p99 is not None and p99 <= target_p99_ms
                  and s["shed_rate"] <= auto["shed_rate"] + 1e-9)
        if not slo_ok:
            beats, how = True, ("static misses the SLO (p99 over "
                                "target, or sheds more at the peak)")
        elif auto_ok and auto["replica_hours"] < s["replica_hours"]:
            beats, how = True, "met the SLO at fewer replica-hours"
        else:
            beats, how = False, "static config not dominated"
        per.append({"config": s["config"],
                    "ttft_p99_ms": p99,
                    "shed_rate": s["shed_rate"],
                    "replica_hours": s["replica_hours"],
                    "static_meets_slo": slo_ok,
                    "beats": beats, "how": how})
    # "best static" ranks shed rate BEFORE p99: a config shedding half
    # its traffic has a flattering p99 on what little it admitted
    best = min((s for s in sweep if s.get("ttft_p99_ms") is not None),
               key=lambda s: (s["shed_rate"], s["ttft_p99_ms"],
                              s["replica_hours"]),
               default=None)
    shed_ok = (best is not None
               and auto["shed_rate"] <= best["shed_rate"] + 1e-9)
    return {
        "valid": valid,
        "autoscale_meets_slo": auto_ok,
        "beats_all_static": valid and auto_ok and shed_ok
        and all(p["beats"] for p in per),
        "shed_at_peak_ok": shed_ok,
        "best_static": best["config"] if best else None,
        "per_config": per,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop disaggregated-serving load harness")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--rate", type=float, default=8.0,
                    help="mean arrival rate, requests/s")
    ap.add_argument("--arrival", default="burst",
                    choices=["uniform", "burst", "diurnal"])
    ap.add_argument("--burst-size", type=int, default=8)
    ap.add_argument("--zipf-a", type=float, default=1.1)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--slow-frac", type=float, default=0.125,
                    help="fraction of slow clients (token-paced drain)")
    ap.add_argument("--token-sleep", type=float, default=0.02)
    ap.add_argument("--distinct", type=int, default=8)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--pool-blocks", type=int, default=None,
                    help="prefill KV pool blocks (default: "
                         "resolve_pool_config's sizing, which doubles "
                         "under --kv-int8; an explicit value is "
                         "honored as-is)")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--prefill-replicas", type=int, default=1)
    ap.add_argument("--decode-replicas", type=int, default=1)
    ap.add_argument("--queue-depth", type=int, default=8,
                    help="router backlog bound per decode replica")
    ap.add_argument("--cluster", action="store_true",
                    help="run the tiers as actors on a local cluster "
                         "(real chunk-fabric transfers)")
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline_s: requests past it "
                         "shed with cause 'deadline' (slow clients "
                         "exercise the edge)")
    ap.add_argument("--http", action="store_true",
                    help="mixed interactive+batch storm over real "
                         "sockets against the OpenAI-compatible "
                         "gateway (serve/gateway.py): batch decodes "
                         "fill every slot, surplus batch sheds with an "
                         "attributed cause, interactive arrivals "
                         "preempt and must hold the TTFT SLO; every "
                         "completed stream is checked bit-identical "
                         "against a serial engine oracle")
    ap.add_argument("--http-max-batch", type=int, default=3,
                    help="engine slots in --http mode (all of them "
                         "are seized by batch fillers at t=0)")
    ap.add_argument("--http-batch", type=int, default=5,
                    help="total batch requests in --http mode; the "
                         "surplus past --http-max-batch arrives on a "
                         "full system and must shed")
    ap.add_argument("--http-interactive", type=int, default=3,
                    help="interactive probes in --http mode, arriving "
                         "mid-decode so they must preempt")
    ap.add_argument("--http-batch-new", type=int, default=600,
                    help="batch decode budget in --http mode; sets "
                         "the engine-production window preemption "
                         "must land inside")
    ap.add_argument("--http-interactive-new", type=int, default=24,
                    help="interactive decode budget in --http mode")
    ap.add_argument("--http-slo-ms", type=float, default=2000.0,
                    help="interactive TTFT p99 SLO the --http verdict "
                         "enforces")
    ap.add_argument("--chaos", action="store_true",
                    help="serving-fault acceptance run (implies "
                         "--cluster): a clean replay vs the same "
                         "replay with a scripted replica kill; records "
                         "failover recovery impact + the zero-dropped/"
                         "bit-identical verdict")
    ap.add_argument("--chaos-role", default="decode",
                    choices=["prefill", "decode"],
                    help="which tier's replica 0 the chaos plan kills")
    ap.add_argument("--chaos-at", default="token:30",
                    help="kill point: 'token:K' (the replica's K-th "
                         "served token, mid-stream) or 'request:N' "
                         "(its N-th request); counters reset at "
                         "measurement start, so N/K count MEASURED "
                         "traffic only (warm-up excluded)")
    ap.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant LoRA acceptance run: N tenants "
                         "drawn Zipf over N adapters against pools "
                         "holding --lora-pool-slots (< N shows "
                         "paging), one mid-run adapter publish for "
                         "the hottest tenant; records hit rate, "
                         "page-in amortization, per-tenant isolation, "
                         "mixed-vs-sequential bit-identity, and the "
                         "publish-no-stall TTFT check")
    ap.add_argument("--tenant-zipf", type=float, default=1.1,
                    help="Zipf exponent of the tenant draw")
    ap.add_argument("--lora-pool-slots", type=int, default=8,
                    help="adapter-pool rows per replica (deliberately "
                         "< --tenants so cold tenants page)")
    ap.add_argument("--lora-rank", type=int, default=4)
    ap.add_argument("--speculate", type=int, default=0, metavar="K",
                    help="speculative-decoding comparison: replay the "
                         "same Zipf schedule unspeculated, then with "
                         "k-token prompt-lookup drafts verified per "
                         "tick; the verdict gates on >=2x tokens/s "
                         "with bit-identical greedy outputs")
    ap.add_argument("--kv-int8", action="store_true",
                    help="int8 KV blocks (per-block-channel scales, "
                         "doubled default pool); with --speculate adds "
                         "the int8 comparison run to the record")
    ap.add_argument("--kvplane", action="store_true",
                    help="tiered-KV-plane acceptance run (implies "
                         "--cluster): a Zipf replay whose distinct-"
                         "block working set exceeds one replica's HBM "
                         "pool, replayed with the plane off (single-"
                         "tier baseline), on (host-arena spill/"
                         "re-adopt + prefix-directory routing + a "
                         "cold-swapped-tier tier-3 replay from the "
                         "object store), and on under a scripted "
                         "evict_storm; the verdict gates on strictly "
                         "more reused tokens than the baseline, "
                         "tier-2 AND tier-3 engagement, bit-identical "
                         "outputs everywhere, and zero wrong outputs "
                         "through the storm")
    ap.add_argument("--kvplane-arena-mb", type=int, default=64,
                    help="per-replica host-arena bound in --kvplane "
                         "mode")
    ap.add_argument("--kvplane-tail-blocks", type=int, default=4,
                    help="distinct full blocks per prompt tail in "
                         "--kvplane mode (sizes the working set past "
                         "--pool-blocks, default 16 there; the tiny "
                         "config's 128-token max_seq_len caps "
                         "sys + tail + --max-new)")
    ap.add_argument("--colocated-baseline", action="store_true",
                    help="also run the single-engine colocated path "
                         "for comparison")
    ap.add_argument("--autoscale", action="store_true",
                    help="run the SLO-driven autoscaler "
                         "(serve/autoscale.py) instead of a static "
                         "provisioning; tiers start at the minimum")
    ap.add_argument("--compare-static", default="",
                    help='static (P,D) sweep as comma "PxD" configs, '
                         'e.g. "1x1,2x1,1x2,2x2": run each, plus the '
                         "autoscaled run, and record the verdict "
                         "(implies --autoscale)")
    ap.add_argument("--target-p99-ms", type=float, default=None,
                    help="TTFT SLO for the policy AND the verdict "
                         "(default: RAY_TPU_AUTOSCALE_TARGET_P99_MS)")
    ap.add_argument("--min-prefill", type=int, default=1)
    ap.add_argument("--max-prefill", type=int, default=2)
    ap.add_argument("--min-decode", type=int, default=1)
    ap.add_argument("--max-decode", type=int, default=2)
    ap.add_argument("--up-delay", type=float, default=1.0)
    ap.add_argument("--down-delay", type=float, default=5.0)
    ap.add_argument("--cooldown", type=float, default=2.0)
    ap.add_argument("--autoscale-interval", type=float, default=0.25)
    ap.add_argument("--drain-grace", type=float, default=30.0)
    ap.add_argument("--window-s", type=float, default=None,
                    help="signal recency window (sets "
                         "RAY_TPU_AUTOSCALE_WINDOW_S for the run; a "
                         "compressed diurnal needs a window shorter "
                         "than its day)")
    ap.add_argument("--out", default="", help="also write JSON here")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.window_s is not None:
        import os as os_mod

        os_mod.environ["RAY_TPU_AUTOSCALE_WINDOW_S"] = str(args.window_s)

    import jax

    from ray_tpu.models.llama import LlamaConfig, llama_init

    config = LlamaConfig.tiny()
    params = llama_init(config, jax.random.PRNGKey(args.seed))
    prompts = make_prompts(config, n_distinct=args.distinct,
                           block_size=args.block_size, seed=args.seed)

    use_cluster = args.cluster or args.chaos or args.kvplane
    if use_cluster:
        import ray_tpu

        # every mode's replica actors (default 1 CPU per lease) must
        # fit: the plain tiers, the autoscaler's max bounds, AND the
        # largest static config in the --compare-static sweep
        sweep_max = max(
            (int(p) + int(d) for p, _, d in
             (s.partition("x") for s in args.compare_static.split(",")
              if s)), default=0)
        # chaos mode runs >=2 decode replicas plus a self-heal
        # replacement beside the prefill tier
        chaos_need = (args.prefill_replicas + 1
                      + max(2, args.decode_replicas) + 1
                      if args.chaos else 0)
        # the cold-swap phase holds the retired prefill tier alive
        # (its tier-3 refs) BESIDE the fresh one
        kvplane_need = (2 * max(2, args.prefill_replicas)
                        + args.decode_replicas if args.kvplane else 0)
        ray_tpu.init(num_cpus=max(4, args.prefill_replicas
                                  + args.decode_replicas,
                                  args.max_prefill + args.max_decode,
                                  sweep_max, chaos_need,
                                  kvplane_need) + 2,
                     _system_config={"log_to_driver": 0},
                     ignore_reinit_error=True)
    record: Dict[str, Any] = {
        "metric": "disagg_serve_load",
        "platform": jax.devices()[0].platform,
        "cluster": use_cluster,
        "prefill_replicas": args.prefill_replicas,
        "decode_replicas": args.decode_replicas,
        "max_batch": args.max_batch,
        "queue_depth": args.queue_depth,
    }
    load_kw = dict(n_requests=args.requests, max_new_tokens=args.max_new,
                   rate_rps=args.rate, arrival=args.arrival,
                   burst_size=args.burst_size, zipf_a=args.zipf_a,
                   slow_client_frac=args.slow_frac,
                   token_sleep_s=args.token_sleep,
                   deadline_s=args.deadline, seed=args.seed)
    # --kv-int8 without --speculate: int8 tiers for whatever mode runs
    args._speculate_k = 0
    args._kv_int8 = bool(args.kv_int8 and not args.speculate)
    args._kvplane = None
    if args.pool_blocks is None and not (args.speculate
                                         or args.kv_int8
                                         or args.kvplane):
        # pre-existing modes keep their historical 64-block pool so
        # reruns stay comparable with the recorded BENCH_* baselines;
        # the spec/int8 modes flow None through to resolve_pool_config
        # so the int8 doubling is the real mechanism, not the harness
        args.pool_blocks = 64
    if args.kvplane:
        # deep distinct tails + a deliberately small pool: the
        # working set (sys + n_distinct * tail blocks) must exceed
        # one replica's HBM pool or no tier below it ever engages
        # enough distinct tails that each replica's SHARE of the
        # working set (directory affinity partitions prompts across
        # holders) still outruns its pool
        prompts = _kvplane_prompts(
            config, n_distinct=max(args.distinct, 10),
            block_size=args.block_size,
            tail_blocks=args.kvplane_tail_blocks, seed=args.seed)
        if args.pool_blocks is None:
            args.pool_blocks = 16
        # int8 pools: the spill/publish wire format is the raw int8
        # pool block, so tier-2/tier-3 round trips are byte-exact and
        # the bit-identical-vs-reference verdict is a hard gate (fp
        # pools quantize on spill — rtol-close only)
        args._kv_int8 = True
        # identity harness, not a tail-latency storm: uniform modest
        # arrivals bound concurrent prefills per replica, so an arena
        # re-adopt never loses the pin race for pool blocks (an
        # alloc-starved re-adopt would shorten the match and change
        # the split vs the reference)
        load_kw = dict(load_kw, arrival="uniform",
                       rate_rps=min(args.rate, 4.0),
                       slow_client_frac=0.0, token_sleep_s=0.0)
        record.update(metric="kvplane_tiered_load",
                      prefill_replicas=max(2, args.prefill_replicas),
                      pool_blocks=args.pool_blocks,
                      arena_mb=args.kvplane_arena_mb,
                      kv_int8=True, rate_rps=load_kw["rate_rps"],
                      arrival="uniform")
        try:
            record.update(_kvplane_record(params, config, args,
                                          prompts, load_kw))
            top = record["kvplane"]
            record.update(value=top["tokens_per_sec"],
                          unit="tokens/s",
                          ttft_p50_ms=top["ttft_p50_ms"],
                          ttft_p99_ms=top["ttft_p99_ms"],
                          shed_rate=top["shed_rate"],
                          directory_hit_rate=record["verdict"][
                              "directory_hit_rate"])
        finally:
            import ray_tpu

            ray_tpu.shutdown()
        line = json.dumps(record)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=1)
        print(line)
        return 0 if record.get("verdict", {}).get("pass") else 1
    if args.http:
        record.update(metric="gateway_http_load",
                      max_batch=args.http_max_batch,
                      queue_depth=0,
                      slo_ms=args.http_slo_ms)
        try:
            record.update(_http_record(params, config, args, prompts))
            inter = record["by_class"]["interactive"]
            record.update(value=inter["ttft_p99_ms"], unit="ms",
                          ttft_p50_ms=inter["ttft_p50_ms"],
                          ttft_p99_ms=inter["ttft_p99_ms"],
                          shed_rate=(record["by_class"]["batch"]["shed"]
                                     / max(1, record["n_requests"])))
        finally:
            if use_cluster:
                import ray_tpu

                ray_tpu.shutdown()
        line = json.dumps(record)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=1)
        print(line)
        return 0 if record.get("verdict", {}).get("pass") else 1
    if args.speculate:
        record.update(metric="speculative_decode_load",
                      speculate_k=args.speculate,
                      kv_int8=bool(args.kv_int8))
        try:
            record.update(_spec_record(params, config, args, prompts,
                                       load_kw, use_cluster))
            top = record["speculate"]
            record.update(value=top["tokens_per_sec"], unit="tokens/s",
                          ttft_p50_ms=top["ttft_p50_ms"],
                          ttft_p99_ms=top["ttft_p99_ms"],
                          shed_rate=top["shed_rate"],
                          speedup=record["verdict"]["speedup"],
                          acceptance_rate=record["verdict"][
                              "acceptance_rate"])
        finally:
            if use_cluster:
                import ray_tpu

                ray_tpu.shutdown()
        line = json.dumps(record)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=1)
        print(line)
        return 0 if record.get("verdict", {}).get("pass") else 1
    if args.chaos:
        record.update(metric="servefault_chaos",
                      decode_replicas=max(2, args.decode_replicas))
        try:
            record.update(_chaos_record(params, config, args, prompts,
                                        load_kw))
            top = record["chaos"]
            record.update(value=top["tokens_per_sec"], unit="tokens/s",
                          ttft_p50_ms=top["ttft_p50_ms"],
                          ttft_p99_ms=top["ttft_p99_ms"],
                          shed_rate=top["shed_rate"])
        finally:
            import ray_tpu

            ray_tpu.shutdown()
        line = json.dumps(record)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=1)
        print(line)
        return 0 if record.get("verdict", {}).get("pass") else 1
    if args.tenants:
        record.update(metric="lora_serve_load", tenants=args.tenants,
                      tenant_zipf=args.tenant_zipf,
                      lora_pool_slots=args.lora_pool_slots,
                      lora_rank=args.lora_rank)
        try:
            top = _lora_record(params, config, args, prompts, load_kw,
                               use_cluster)
            record["lora_run"] = top
            record.update(value=top["tokens_per_sec"],
                          unit="tokens/s",
                          ttft_p50_ms=top["ttft_p50_ms"],
                          ttft_p99_ms=top["ttft_p99_ms"],
                          shed_rate=top["shed_rate"],
                          lora_hit_rate=top["lora"]["hit_rate"])
        finally:
            if use_cluster:
                import ray_tpu

                ray_tpu.shutdown()
        line = json.dumps(record)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=1)
        print(line)
        return 0 if record.get("lora_run", {}).get(
            "lora", {}).get("verdict", {}).get("pass") else 1
    if args.compare_static or args.autoscale:
        from ray_tpu.serve.autoscale import default_target_p99_ms

        target = (args.target_p99_ms if args.target_p99_ms is not None
                  else default_target_p99_ms())
        record.update(metric="autoscale_serve_load",
                      target_p99_ms=target)
        try:
            sweep: List[Dict[str, Any]] = []
            for spec in [s for s in args.compare_static.split(",") if s]:
                pf_n, _, dec_n = spec.partition("x")
                sweep.append(_static_run(
                    params, config, args, use_cluster, prompts,
                    load_kw, int(pf_n), int(dec_n)))
            record["autoscale_run"] = _autoscaled_run(
                params, config, args, use_cluster, prompts, load_kw,
                target)
            if sweep:
                record["sweep"] = sweep
                record["verdict"] = compare_verdict(
                    record["autoscale_run"], sweep, target)
            top = record["autoscale_run"]
            record.update(value=top["tokens_per_sec"], unit="tokens/s",
                          ttft_p50_ms=top["ttft_p50_ms"],
                          ttft_p99_ms=top["ttft_p99_ms"],
                          shed_rate=top["shed_rate"],
                          replica_hours=top["replica_hours"])
        finally:
            if use_cluster:
                import ray_tpu

                ray_tpu.shutdown()
        line = json.dumps(record)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(record, f, indent=1)
        print(line)
        return 0

    try:
        router, prefill, decode, cleanup = _build_tiers(
            params, config, args, use_cluster)
        try:
            # warm the compile caches off the clock: each distinct
            # prompt shape costs one prefill compile on first sight.
            # Snapshot the counters after warm-up so the recorded
            # accounting covers exactly the measured open-loop run —
            # published==fetched must cross-check against n_requests'
            # expected KV bytes, not n_requests + warm-up traffic.
            for p in prompts:
                router.generate(p, 2)
            warm_kv = collect_kv_accounting(prefill, decode)
            warm_rt = router.stats()
            record["disagg"] = run_load(router, prompts, **load_kw)
            kv = collect_kv_accounting(prefill, decode)
            record["disagg"]["kv_transfer"] = {
                k: v - warm_kv.get(k, 0) for k, v in kv.items()}
            record["disagg"]["router"] = {
                k: (v - warm_rt[k]
                    if k in ("dispatched", "completed", "shed") else v)
                for k, v in router.stats().items()}
            router.publish_telemetry(force=True)
        finally:
            cleanup()
        if args.colocated_baseline:
            from ray_tpu.models.engine import ContinuousBatchingEngine
            from ray_tpu.serve.disagg import DisaggRouter

            eng = ContinuousBatchingEngine(
                params, config, max_batch=args.max_batch,
                kv_block_size=args.block_size,
                kv_pool_blocks=args.pool_blocks)
            try:
                colo = DisaggRouter(colocated=eng,
                                    max_queue_depth=args.queue_depth)
                for p in prompts:
                    colo.generate(p, 2)
                warm_rt = colo.stats()
                record["colocated"] = run_load(colo, prompts, **load_kw)
                record["colocated"]["kv_transfer"] = {
                    "transfers": 0, "published_bytes": 0,
                    "fetched_bytes": 0, "shm_bytes": 0, "rpc_bytes": 0}
                record["colocated"]["router"] = {
                    k: (v - warm_rt[k]
                        if k in ("dispatched", "completed", "shed")
                        else v)
                    for k, v in colo.stats().items()}
            finally:
                eng.stop()
        # the headline numbers are the disagg run's
        top = record["disagg"]
        record.update(value=top["tokens_per_sec"], unit="tokens/s",
                      ttft_p50_ms=top["ttft_p50_ms"],
                      ttft_p99_ms=top["ttft_p99_ms"],
                      shed_rate=top["shed_rate"])
    finally:
        if use_cluster:
            import ray_tpu

            ray_tpu.shutdown()
    line = json.dumps(record)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(record, f, indent=1)
    print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
