/* ray_tpu C++ runtime API — put/get/submit from native tasks.
 *
 * Reference analog: the C++ worker API's driver surface
 * (/root/reference/cpp/include/ray/api.h: ray::Put, ray::Get,
 * ray::Task(...).Remote()). The v1 bytes ABI (ray_tpu_task.h) keeps
 * native code pure-compute; this v2 ABI hands the task a table of
 * runtime entry points so C++ can hold object refs, create objects,
 * and fan out subtasks — without linking against the framework: the
 * hosting worker passes the table in, every pointer lives only for the
 * duration of the call.
 *
 * v2 contract — export with C linkage:
 *
 *     extern "C" int64_t my_task(const ray_tpu_api_t* api,
 *                                const uint8_t* in, size_t in_len,
 *                                uint8_t** out, size_t* out_len);
 *
 * Object ids are opaque NUL-terminated hex strings (up to 64 chars);
 * treat them as strings, never fixed-width — id buffers must be at
 * least RAY_TPU_OBJECT_ID_BUF bytes. All entry points return 0 on
 * success. get()'s timeout_s: negative blocks forever, 0 polls
 * (returns 11/EAGAIN when not ready), positive bounds the wait. Ids
 * minted by put()/submit() are pinned in the hosting worker until
 * release() — release what you mint, or the objects live until the
 * worker exits. Ids are PROCESS-LOCAL: get()/release() only resolve
 * ids minted in the same worker process, so pass VALUES (bytes)
 * across task boundaries, not id strings — a subtask may execute in a
 * different worker where the parent's ids are unknown (ENOENT).
 *
 * Run:  f = ray_tpu.util.cpp.cpp_function(lib, sym, api=True)
 */
#ifndef RAY_TPU_API_H_
#define RAY_TPU_API_H_

#include "ray_tpu_task.h"

#define RAY_TPU_OBJECT_ID_BUF 65

typedef struct ray_tpu_api {
  void* ctx; /* pass as the first argument to every entry point */

  /* Store `len` bytes as a cluster object owned by this worker;
   * writes the object id into id_out (RAY_TPU_OBJECT_ID_BUF bytes). */
  int64_t (*put)(void* ctx, const uint8_t* data, size_t len,
                 char* id_out);

  /* Fetch an object's bytes (ids minted by this API). On success *out
   * is a malloc'd buffer of *out_len bytes — free with free_buf. */
  int64_t (*get)(void* ctx, const char* object_id, double timeout_s,
                 uint8_t** out, size_t* out_len);

  /* Submit another v2 symbol from the SAME library as a cluster task;
   * writes the result object id into id_out. */
  int64_t (*submit)(void* ctx, const char* symbol, const uint8_t* arg,
                    size_t arg_len, char* id_out);

  /* Drop this worker's pin on an id from put()/submit(). */
  int64_t (*release)(void* ctx, const char* object_id);

  void (*free_buf)(uint8_t* p);

  /* ---- v2.1 appended entry points (actor surface; reference analog:
   * ray::Actor(...).Remote() / ActorHandle.Task() in
   * /root/reference/cpp/include/ray/api.h). Fields are appended so v2
   * binaries keep working unchanged. Actor-handle ids are PROCESS-LOCAL
   * like object ids. ---- */

  /* Create a cluster actor whose methods are v1-ABI symbols of the SAME
   * library (comma-separated in `methods`); `init_symbol` (may be NULL)
   * runs once at construction with the init payload. Writes the handle
   * id into id_out (RAY_TPU_OBJECT_ID_BUF bytes). */
  int64_t (*create_actor)(void* ctx, const char* methods,
                          const char* init_symbol, const uint8_t* init_arg,
                          size_t init_len, char* id_out);

  /* Invoke a declared method symbol on the actor; writes the result
   * object id into id_out (get/release it like any other id). */
  int64_t (*call_actor)(void* ctx, const char* actor_id,
                        const char* method, const uint8_t* arg,
                        size_t arg_len, char* id_out);

  /* Terminate the actor and drop the handle. */
  int64_t (*kill_actor)(void* ctx, const char* actor_id);
} ray_tpu_api_t;

#endif  /* RAY_TPU_API_H_ */
