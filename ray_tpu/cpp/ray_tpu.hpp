/* ray_tpu typed C++ surface — ObjectRef<T> / typed Put/Get/Submit over
 * the v2 C ABI (ray_tpu_api.h).
 *
 * Reference analog: /root/reference/cpp/include/ray/api.h — ray::Put
 * returning ray::ObjectRef<T>, ray::Get, ray::Task(...).Remote() —
 * rebuilt header-only over this runtime's function-table ABI so native
 * tasks never link against the framework.
 *
 *   extern "C" int64_t my_task(const ray_tpu_api_t* api,
 *                              const uint8_t* in, size_t in_len,
 *                              uint8_t** out, size_t* out_len) {
 *     ray_tpu::Runtime rt(api);
 *     Vec3 v{1, 2, 3};
 *     auto ref = rt.Put(v);                       // ObjectRef<Vec3>
 *     Vec3 back = rt.Get(ref);                    // typed round-trip
 *     auto sub = rt.Submit<double>("other_sym", payload);
 *     double r = rt.Get(sub, /\*timeout_s=\*\/30.0);
 *     ...
 *   }
 *
 * Serialization: trivially-copyable T's are byte-copied; std::string
 * and std::vector<trivially-copyable> ship their contents. That covers
 * structs-of-PODs without a codegen step; anything richer should be
 * serialized by the caller into bytes (the v2 ABI is always available
 * underneath via Runtime::raw()).
 *
 * Ownership: ObjectRef releases its pin (api->release) when the last
 * copy is destroyed — mirroring the reference's reference-counted
 * ObjectRef (api.h ObjectRef dtor). Ids are process-local (see
 * ray_tpu_api.h): pass values across task boundaries, not refs.
 */
#ifndef RAY_TPU_HPP_
#define RAY_TPU_HPP_

#include <cstring>
#include <memory>
#include <stdexcept>
#include <string>
#include <type_traits>
#include <vector>

#include "ray_tpu_api.h"

namespace ray_tpu {

class RayError : public std::runtime_error {
 public:
  RayError(const std::string& what, int64_t code)
      : std::runtime_error(what + " (rc=" + std::to_string(code) + ")"),
        code_(code) {}
  int64_t code() const { return code_; }

 private:
  int64_t code_;
};

namespace detail {

template <typename T>
struct Codec {
  static_assert(std::is_trivially_copyable<T>::value,
                "ray_tpu::Codec<T>: T must be trivially copyable (or use "
                "the std::string / std::vector specializations, or the "
                "raw bytes ABI)");
  static std::vector<uint8_t> encode(const T& v) {
    std::vector<uint8_t> buf(sizeof(T));
    std::memcpy(buf.data(), &v, sizeof(T));
    return buf;
  }
  static T decode(const uint8_t* data, size_t len) {
    if (len != sizeof(T)) {
      throw RayError("typed Get: payload size " + std::to_string(len) +
                         " != sizeof(T) " + std::to_string(sizeof(T)),
                     22);
    }
    T v;
    std::memcpy(&v, data, sizeof(T));
    return v;
  }
};

template <>
struct Codec<std::string> {
  static std::vector<uint8_t> encode(const std::string& s) {
    return std::vector<uint8_t>(s.begin(), s.end());
  }
  static std::string decode(const uint8_t* data, size_t len) {
    return std::string(reinterpret_cast<const char*>(data), len);
  }
};

template <typename E>
struct Codec<std::vector<E>> {
  static_assert(std::is_trivially_copyable<E>::value,
                "vector elements must be trivially copyable");
  static std::vector<uint8_t> encode(const std::vector<E>& v) {
    std::vector<uint8_t> buf(v.size() * sizeof(E));
    if (!v.empty()) std::memcpy(buf.data(), v.data(), buf.size());
    return buf;
  }
  static std::vector<E> decode(const uint8_t* data, size_t len) {
    if (len % sizeof(E)) {
      throw RayError("typed Get: payload not a whole number of elements",
                     22);
    }
    std::vector<E> v(len / sizeof(E));
    if (len) std::memcpy(v.data(), data, len);
    return v;
  }
};

/* Shared pin: api->release fires once, when the last ref copy dies. */
class Pin {
 public:
  Pin(const ray_tpu_api_t* api, std::string id)
      : api_(api), id_(std::move(id)) {}
  ~Pin() {
    if (api_ != nullptr) api_->release(api_->ctx, id_.c_str());
  }
  Pin(const Pin&) = delete;
  Pin& operator=(const Pin&) = delete;
  const std::string& id() const { return id_; }

 private:
  const ray_tpu_api_t* api_;
  std::string id_;
};

}  // namespace detail

/* Typed handle to a cluster object — reference api.h ObjectRef<T>. */
template <typename T>
class ObjectRef {
 public:
  ObjectRef() = default;
  ObjectRef(const ray_tpu_api_t* api, std::string id)
      : pin_(std::make_shared<detail::Pin>(api, std::move(id))) {}
  const std::string& ID() const {
    static const std::string kEmpty;
    return pin_ ? pin_->id() : kEmpty;
  }
  bool Valid() const { return static_cast<bool>(pin_); }

 private:
  std::shared_ptr<detail::Pin> pin_;
};

/* Typed actor handle — reference api.h ActorHandle<T>. Methods are v1
 * symbol names of the same library; Call<R, Arg> decodes the result
 * object as R. Kill() is explicit (handles are process-local ids, not
 * refcounted pins). */
class ActorHandle {
 public:
  ActorHandle() = default;
  ActorHandle(const ray_tpu_api_t* api, std::string id)
      : api_(api), id_(std::move(id)) {}
  const std::string& ID() const { return id_; }
  bool Valid() const { return api_ != nullptr; }

  template <typename R, typename Arg>
  ObjectRef<R> Call(const char* method, const Arg& arg) const {
    std::vector<uint8_t> buf = detail::Codec<Arg>::encode(arg);
    char id[RAY_TPU_OBJECT_ID_BUF] = {0};
    int64_t rc = api_->call_actor(api_->ctx, id_.c_str(), method,
                                  buf.data(), buf.size(), id);
    if (rc != 0) {
      throw RayError(std::string("actor Call of ") + method + " failed",
                     rc);
    }
    return ObjectRef<R>(api_, id);
  }

  void Kill() {
    if (api_ != nullptr) {
      api_->kill_actor(api_->ctx, id_.c_str());
      api_ = nullptr;
    }
  }

 private:
  const ray_tpu_api_t* api_ = nullptr;
  std::string id_;
};

class Runtime {
 public:
  explicit Runtime(const ray_tpu_api_t* api) : api_(api) {}

  template <typename T>
  ObjectRef<T> Put(const T& value) {
    std::vector<uint8_t> buf = detail::Codec<T>::encode(value);
    char id[RAY_TPU_OBJECT_ID_BUF] = {0};
    int64_t rc = api_->put(api_->ctx, buf.data(), buf.size(), id);
    if (rc != 0) throw RayError("Put failed", rc);
    return ObjectRef<T>(api_, id);
  }

  /* timeout_s < 0 blocks forever (the default, like reference
   * ray::Get); 0 polls; > 0 bounds the wait. */
  template <typename T>
  T Get(const ObjectRef<T>& ref, double timeout_s = -1.0) {
    uint8_t* out = nullptr;
    size_t out_len = 0;
    int64_t rc = api_->get(api_->ctx, ref.ID().c_str(), timeout_s, &out,
                           &out_len);
    if (rc != 0) throw RayError("Get of " + ref.ID() + " failed", rc);
    try {
      T v = detail::Codec<T>::decode(out, out_len);
      api_->free_buf(out);
      return v;
    } catch (...) {
      api_->free_buf(out);
      throw;
    }
  }

  /* Submit another extern-C v2 symbol from the same library; the result
   * object holds the subtask's output bytes, decoded as R on Get. */
  template <typename R, typename Arg>
  ObjectRef<R> Submit(const char* symbol, const Arg& arg) {
    std::vector<uint8_t> buf = detail::Codec<Arg>::encode(arg);
    char id[RAY_TPU_OBJECT_ID_BUF] = {0};
    int64_t rc =
        api_->submit(api_->ctx, symbol, buf.data(), buf.size(), id);
    if (rc != 0) throw RayError(std::string("Submit of ") + symbol +
                                    " failed",
                                rc);
    return ObjectRef<R>(api_, id);
  }

  /* Create an actor whose methods are v1 symbols of this library —
   * reference ray::Actor(...).Remote(). `methods` is comma-separated;
   * init_symbol may be nullptr. */
  template <typename Arg>
  ActorHandle CreateActor(const char* methods, const char* init_symbol,
                          const Arg& init) {
    std::vector<uint8_t> buf = detail::Codec<Arg>::encode(init);
    char id[RAY_TPU_OBJECT_ID_BUF] = {0};
    int64_t rc = api_->create_actor(api_->ctx, methods, init_symbol,
                                    buf.data(), buf.size(), id);
    if (rc != 0) throw RayError("CreateActor failed", rc);
    return ActorHandle(api_, id);
  }

  const ray_tpu_api_t* raw() const { return api_; }

 private:
  const ray_tpu_api_t* api_;
};

}  // namespace ray_tpu

#endif  /* RAY_TPU_HPP_ */
