/* ray_tpu C++ task ABI — native tasks on the ray_tpu transport.
 *
 * Reference analog: the C++ worker API (src/ray/core_worker C++ task
 * surface). ray_tpu's wire protocol is python-pickled frames, so instead
 * of reimplementing serialization in C++, native tasks speak a stable
 * bytes-in/bytes-out C ABI and the (already running) worker process
 * loads them via dlopen/ctypes: zero build-system coupling, any
 * encoding the user likes (raw structs, msgpack, json, protobuf).
 *
 * Contract — export with C linkage:
 *
 *     extern "C" int64_t my_task(const uint8_t* in, size_t in_len,
 *                                uint8_t** out, size_t* out_len);
 *
 *   - return 0 on success, nonzero on failure (surfaces as a
 *     TaskError naming the code);
 *   - on success, *out must point to a malloc()'d buffer of *out_len
 *     bytes; the runtime frees it with free() after copying;
 *   - the input buffer is owned by the runtime and valid only for the
 *     duration of the call.
 *
 * Build:  g++ -O2 -shared -fPIC -o libmytasks.so mytasks.cc
 * Run:    f = ray_tpu.util.cpp.cpp_function("./libmytasks.so", "my_task")
 *         ray_tpu.get(f.remote(payload_bytes))
 *
 * RAY_TPU_TASK_RETURN copies a C++ container's bytes into a malloc'd
 * output buffer — the one-liner for the common case.
 */
#ifndef RAY_TPU_TASK_H_
#define RAY_TPU_TASK_H_

#include <cstdint>
#include <cstdlib>
#include <cstring>

#define RAY_TPU_TASK_RETURN(out, out_len, data, len)                   \
  do {                                                                 \
    *(out) = static_cast<uint8_t*>(std::malloc(len));                  \
    if (*(out) == nullptr) return -12; /* ENOMEM */                    \
    std::memcpy(*(out), (data), (len));                                \
    *(out_len) = (len);                                                \
  } while (0)

#endif  // RAY_TPU_TASK_H_
