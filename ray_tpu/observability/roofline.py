"""Step-time oracle: roofline prediction, validation, attribution.

Shardlint prices a layout in bytes-over-DCN (`analysis.collectives`);
the flight recorder prices a run in milliseconds (`step_timer`). This
module is the bridge: a per-generation ICI/DCN bandwidth+latency table
(the comms twin of ``flops.PEAK_FLOPS_BF16``) turns a layout's traced
collectives into a predicted ``{device_step, ici_wait, dcn_wait}``
step-time breakdown, and a validation harness replays flight-recorder
measurements against the prediction so the model stays falsifiable.

Model ("Exploring the limits of Concurrency in ML Training on Google
TPUs", arXiv:2011.03641 — the roofline shape per parallelism mix):

- compute roofline:  ``flops_per_step / peak_flops_total``;
- comms roofline:    per collective, the ring traffic is split by link
  class — the DCN share from ``CollectiveUse.dcn_bytes`` and the ICI
  remainder from ``CollectiveUse.ring_bytes`` — and each class pays
  ``bytes / bandwidth + hops * latency``;
- the prediction is the SERIAL sum of the three phases: an upper bound
  (real programs overlap comms with compute), which is exactly what the
  fitted calibration factor absorbs.

Constants are approximate public spec figures (per chip, one direction).
They do not need to be exact to be useful: the validation harness fits a
scalar calibration factor against measured steps, so the table only has
to get the SHAPE right (ICI ≫ DCN, newer generations faster). Non-TPU
backends get documented nominal constants — on the CPU tier-1 cluster
the oracle validates plumbing and calibration math, not absolute
numbers.

Runtime surface (the repo's full treatment): predictions and validation
records push to the conductor (``util.state.oracle_status()``, CLI
``ray_tpu oracle``, dashboard ``/api/oracle``), lazy Prometheus gauges
``ray_tpu_oracle_predicted_step_ms{layout}`` /
``ray_tpu_oracle_residual_ratio{phase}``, and a ``predicted_step_ms``
counter track in the merged timeline.
"""
from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import flops as _flops
from .step_timer import summarize_records

# ------------------------------------------------------- constants table

@dataclass(frozen=True)
class LinkConstants:
    """Per-chip interconnect constants of one device generation.

    ``*_bw`` in bytes/s (one direction, per chip — the divisor for the
    PER-CHIP ring traffic ``CollectiveUse`` computes), ``*_latency_s``
    per ring hop.
    """

    ici_bw: float
    ici_latency_s: float
    dcn_bw: float
    dcn_latency_s: float


# Keyed exactly like flops.PEAK_FLOPS_BF16 (longest prefix wins) — the
# property tests pin the two tables together: every generation with a
# peak-FLOPs entry has link constants, and within each generation the
# link classes are ordered (ICI faster than DCN, ICI hop latency lower).
# ICI figures follow the public per-chip interconnect specs; DCN is the
# per-chip share of the host NIC crossing the slice boundary.
LINK_CONSTANTS: Dict[str, LinkConstants] = {
    "TPU v2": LinkConstants(6.2e10, 1e-6, 3.1e9, 3.0e-5),
    "TPU v3": LinkConstants(8.2e10, 1e-6, 3.1e9, 3.0e-5),
    "TPU v4": LinkConstants(2.4e11, 1e-6, 6.2e9, 2.5e-5),
    "TPU v5 lite": LinkConstants(1.0e11, 1e-6, 6.2e9, 2.5e-5),  # v5e
    "TPU v5e": LinkConstants(1.0e11, 1e-6, 6.2e9, 2.5e-5),
    "TPU v5p": LinkConstants(4.8e11, 1e-6, 1.2e10, 2.5e-5),
    "TPU v5": LinkConstants(4.8e11, 1e-6, 1.2e10, 2.5e-5),
    "TPU v6 lite": LinkConstants(3.6e11, 1e-6, 1.2e10, 2.5e-5),  # v6e
    "TPU v6e": LinkConstants(3.6e11, 1e-6, 1.2e10, 2.5e-5),
}

# Nominal constants for non-TPU backends (the flops.NOMINAL_PEAK_FLOPS
# pattern): off-silicon predictions are only meaningful as a relative
# series, so these just need to be stable, documented, and shaped right.
NOMINAL_LINK_CONSTANTS: Dict[str, LinkConstants] = {
    "cpu": LinkConstants(1.0e10, 1e-6, 1.0e9, 5.0e-5),
    "gpu": LinkConstants(6.0e11, 1e-6, 2.5e10, 2.5e-5),  # NVLink / IB
}

_UNKNOWN_TPU_LINKS = LINK_CONSTANTS["TPU v4"]  # conservative, like flops


def device_link_constants(device: Any = None) -> LinkConstants:
    """Link constants of one device (jax Device or None for the first
    local device) — longest-prefix match, mirroring
    ``flops.device_peak_flops``."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    for name, links in sorted(LINK_CONSTANTS.items(),
                              key=lambda kv: -len(kv[0])):
        if kind.startswith(name):
            return links
    platform = getattr(device, "platform", "") or ""
    if platform == "tpu":
        return _UNKNOWN_TPU_LINKS
    return NOMINAL_LINK_CONSTANTS.get(platform,
                                      NOMINAL_LINK_CONSTANTS["cpu"])


# ------------------------------------------------------------ prediction

#: phases the oracle models; the measured counterpart of their sum is
#: the recorder's device_step (collectives run inside the jitted step).
PREDICTED_PHASES = ("device_step", "ici_wait", "dcn_wait")


def predict_step_time(layout: Any, uses: Sequence[Any],
                      flops_per_step: float,
                      peak_flops_total: float,
                      links: Optional[LinkConstants] = None,
                      calibration: float = 1.0,
                      name: str = "") -> Dict[str, Any]:
    """Analytic roofline prediction for one layout.

    ``layout`` is an ``analysis.shardcheck.MeshLayout``; ``uses`` the
    traced ``CollectiveUse`` list. Returns the compile-excluded
    breakdown {device_step_ms, ici_wait_ms, dcn_wait_ms} plus the
    serial total and the inputs that produced it, all pre-scaled by
    ``calibration`` (1.0 = the raw analytic model).
    """
    links = links or device_link_constants()
    compute_s = (flops_per_step / peak_flops_total
                 if flops_per_step and peak_flops_total else 0.0)
    ici_s = dcn_s = 0.0
    ici_bytes = dcn_bytes = 0.0
    unmodeled: List[str] = []
    for use in uses:
        n, d = use.spans(layout)
        if n <= 1:
            continue
        if not use.modeled():
            unmodeled.append(use.primitive)
        i_b, d_b = use.link_bytes(layout)
        ici_bytes += i_b
        dcn_bytes += d_b
        if i_b > 0:
            ici_s += i_b / links.ici_bw \
                + links.ici_latency_s * max(1, n - d)
        if d_b > 0:
            dcn_s += d_b / links.dcn_bw \
                + links.dcn_latency_s * max(1, d - 1)
    c = float(calibration)
    pred = {
        "layout": name or getattr(layout, "name", "layout"),
        "device_step_ms": compute_s * 1e3 * c,
        "ici_wait_ms": ici_s * 1e3 * c,
        "dcn_wait_ms": dcn_s * 1e3 * c,
        "predicted_step_ms": (compute_s + ici_s + dcn_s) * 1e3 * c,
        "flops_per_step": float(flops_per_step or 0.0),
        "peak_flops_total": float(peak_flops_total or 0.0),
        "ici_bytes": ici_bytes,
        "dcn_bytes": dcn_bytes,
        "n_collectives": len(uses),
        "calibration": c,
    }
    if unmodeled:
        # the oracle names its own blind spots (satellite: collectives
        # emits the matching INFO finding)
        pred["unmodeled_collectives"] = sorted(set(unmodeled))
    return pred


def predict_builtin_layouts(n_devices: int = 8,
                            device: Any = None,
                            calibration: float = 1.0
                            ) -> Dict[str, Dict[str, Any]]:
    """Predictions for every built-in dryrun layout (the
    ``analyze --predict-step-time`` backend). Deviceless apart from the
    local device used to pick constants — layouts trace against
    AbstractMesh exactly as the shardlint gate does."""
    from ..analysis.layouts import trace_builtin_layouts

    peak = _flops.device_peak_flops(device) * n_devices
    links = device_link_constants(device)
    out: Dict[str, Dict[str, Any]] = {}
    for lname, trace in trace_builtin_layouts(n_devices).items():
        out[lname] = predict_step_time(
            trace.layout, trace.uses, trace.flops_per_step, peak,
            links=links, calibration=calibration, name=lname)
        if trace.tokens_per_step:
            out[lname]["tokens_per_step"] = trace.tokens_per_step
    return out


# ------------------------------------------------------------ validation

def calibration_fit(pairs: Sequence[Tuple[float, float]]) -> float:
    """Least-squares-through-origin scale factor over (predicted_ms,
    measured_ms) pairs: the alpha minimizing Σ(m - alpha·p)². 1.0 when
    the pairs carry no signal."""
    num = sum(p * m for p, m in pairs)
    den = sum(p * p for p, _ in pairs)
    return num / den if den > 0 else 1.0


def phase_residuals(prediction: Dict[str, Any],
                    measured: Dict[str, Any]) -> Dict[str, float]:
    """measured/predicted ratios per comparable phase. The recorder's
    device_step lumps compute + comms (collectives run inside the jitted
    step), so it compares against the predicted serial total; total_ms
    additionally carries the unmodeled host phases (data_wait /
    checkpoint / report)."""
    res: Dict[str, float] = {}
    p_total = prediction.get("predicted_step_ms") or 0.0
    m_dev = measured.get("device_step_ms")
    if p_total > 0 and isinstance(m_dev, (int, float)) and m_dev > 0:
        res["device_step"] = m_dev / p_total
    m_total = measured.get("total_ms")
    if p_total > 0 and isinstance(m_total, (int, float)) and m_total > 0:
        res["total"] = m_total / p_total
    return res


def validate_records(prediction: Dict[str, Any],
                     records: Sequence[Dict[str, Any]]
                     ) -> Dict[str, Any]:
    """Pure validation core: replay flight-recorder step records against
    a prediction. Per-phase residuals come from the measured p50s
    (``step_timer.summarize_records`` — one summary implementation for
    the harness, train_progress, and bench), the calibration factor from
    a least-squares fit over every (predicted, measured device_step)
    pair."""
    summary = summarize_records(records)
    phases = summary.get("phases", {})
    p50s = {f"{name}_ms": st["p50_ms"] for name, st in phases.items()}
    measured = {"device_step_ms": p50s.get("device_step_ms"),
                "total_ms": p50s.get("total_ms")}
    p_total = prediction.get("predicted_step_ms") or 0.0
    pairs = [(p_total, float(r["device_step_ms"]))
             for r in records
             if isinstance(r.get("device_step_ms"), (int, float))
             and r["device_step_ms"] > 0] if p_total > 0 else []
    return {
        "layout": prediction.get("layout"),
        "predicted": {k: prediction.get(k)
                      for k in ("device_step_ms", "ici_wait_ms",
                                "dcn_wait_ms", "predicted_step_ms")},
        "measured": {"summary": phases, **measured},
        "residuals": phase_residuals(prediction, measured),
        "calibration": calibration_fit(pairs),
        "n_steps": summary.get("steps", 0),
    }


def validate_run(prediction: Dict[str, Any],
                 run_id: Optional[str] = None,
                 records: Optional[Sequence[Dict[str, Any]]] = None,
                 persist_path: Optional[str] = None) -> Dict[str, Any]:
    """The validation harness: pull a run's flight-recorder records from
    the conductor (or take them directly), compute residuals + the
    fitted calibration factor, record the result on every oracle
    surface, and optionally persist it as JSON so the model's score
    survives the cluster."""
    if records is None:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            raise RuntimeError(
                "no records given and no cluster: call ray_tpu.init() "
                "or pass records= explicitly")
        all_recs = w.conductor.call("get_train_steps", 10_000,
                                    timeout=30.0)
        records = [r for r in all_recs
                   if run_id is None or r.get("run_id") == run_id]
    # Multi-rank runs flatten to one record per rank per step; validate
    # against the LEAD rank only (gang.summarize_run's convention) so a
    # straggler rank cannot skew the p50s and the calibration fit, and
    # n_steps counts steps, not step-rank samples.
    ranks = {r.get("rank") for r in records if r.get("rank") is not None}
    if len(ranks) > 1:
        lead = min(ranks)
        records = [r for r in records if r.get("rank") == lead]
    if not records:
        # also guards an explicitly-passed empty list: a vacuous
        # validation (n_steps=0, calibration=1.0) would read as a
        # perfect fit on every surface
        raise ValueError(
            f"no flight-recorder step records for run {run_id!r}")
    rec = validate_records(prediction, records)
    if not rec["residuals"]:
        # records that carry none of the modeled phases (a train_fn
        # reporting without TrainStep: no device_step_ms) must not land
        # as a calibration=1.0 "perfect fit" on every surface
        raise ValueError(
            f"records for run {run_id!r} carry no comparable phase "
            "(device_step_ms / total_ms) — nothing to validate")
    rec["run_id"] = run_id
    record_validation(rec)
    if persist_path:
        with open(persist_path, "w") as f:
            json.dump(rec, f, indent=1, default=str)
    return rec


# ----------------------------------------------------- prometheus (lazy)
# Created on first record_*() call, never at import (the weights /
# kvcache / disagg pattern — rebound ONCE to a complete dict).

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def oracle_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Gauge

            _metrics = dict(
                predicted=Gauge(
                    "ray_tpu_oracle_predicted_step_ms",
                    "roofline-predicted step time per layout "
                    "(compile-excluded; device_step + ici_wait + "
                    "dcn_wait)",
                    tag_keys=("layout",)),
                residual=Gauge(
                    "ray_tpu_oracle_residual_ratio",
                    "measured/predicted ratio per phase from the last "
                    "oracle validation (1.0 = the model was right)",
                    tag_keys=("phase",)))
    return _metrics


def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker


def record_prediction(layout: str, prediction: Dict[str, Any]) -> None:
    """Publish one layout's prediction to every oracle surface: the
    Prometheus gauge, the conductor aggregate (state API / CLI /
    dashboard), and the merged timeline's predicted-step-time counter
    track. Best-effort without a cluster (the gauge still updates)."""
    oracle_metrics()["predicted"].set(
        float(prediction.get("predicted_step_ms", 0.0)),
        tags={"layout": str(layout)})
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_oracle_prediction", w.worker_id,
                           str(layout), dict(prediction))
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


def record_validation(rec: Dict[str, Any]) -> None:
    """Publish a validation record (residuals + calibration) to every
    oracle surface."""
    m = oracle_metrics()
    for phase, ratio in (rec.get("residuals") or {}).items():
        m["residual"].set(float(ratio), tags={"phase": str(phase)})
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_oracle_validation", w.worker_id,
                           dict(rec))
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


__all__ = ["LINK_CONSTANTS", "LinkConstants", "NOMINAL_LINK_CONSTANTS",
           "PREDICTED_PHASES", "calibration_fit", "device_link_constants",
           "oracle_metrics", "phase_residuals", "predict_builtin_layouts",
           "predict_step_time", "record_prediction", "record_validation",
           "validate_records", "validate_run"]
