"""Gang-wide step aggregation: skew and straggler detection.

Pure functions over the conductor's per-run ring buffer of
``{step -> {rank -> record}}`` (see ConductorHandler.report_train_steps),
so the math is unit-testable with simulated ranks. Per-host step-time
variance is exactly the signal that decided scaling behavior in
"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md): a single slow host gates every synchronous step, so the
summary names it.

A rank is flagged a straggler when, over the trailing window, its step
duration exceeds ``k x median(gang)`` in a persistent fraction of steps
(one garbage-collection hiccup is not a straggler; a consistently slow
host is). ``k`` is env-tunable via RAY_TPU_STRAGGLER_K (default 1.5).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from .step_timer import percentile as _percentile
from .step_timer import summarize_records

DEFAULT_STRAGGLER_K = 1.5
STRAGGLER_WINDOW = 20          # trailing steps examined
STRAGGLER_MIN_FRACTION = 0.6   # slow in >= this fraction of window steps
STRAGGLER_MIN_STEPS = 3        # don't judge a rank on fewer samples


def straggler_k() -> float:
    try:
        return float(os.environ.get("RAY_TPU_STRAGGLER_K", ""))
    except ValueError:
        return DEFAULT_STRAGGLER_K


def _duration_ms(rec: Dict[str, Any]) -> Optional[float]:
    """A record's gang-relevant duration: device step when recorded
    (host-side data stalls are a different pathology), else total."""
    d = rec.get("device_step_ms") or 0.0
    return d if d > 0 else rec.get("total_ms")


def step_skew(by_rank: Dict[int, Dict[str, Any]]) -> Dict[str, float]:
    """min/median/p99/max duration across ranks for ONE step."""
    vals = sorted(v for v in (_duration_ms(r) for r in by_rank.values())
                  if v is not None)
    if not vals:
        return {}
    median = _percentile(vals, 0.5)
    return {
        "min_ms": vals[0],
        "median_ms": median,
        "p99_ms": _percentile(vals, 0.99),
        "max_ms": vals[-1],
        "max_over_median": vals[-1] / median if median > 0 else 0.0,
    }


def find_stragglers(steps: Dict[int, Dict[int, Dict[str, Any]]],
                    k: Optional[float] = None,
                    window: int = STRAGGLER_WINDOW,
                    min_fraction: float = STRAGGLER_MIN_FRACTION
                    ) -> List[int]:
    """Ranks persistently above ``k x median`` in the trailing window.

    Only steps with >= 2 reporting ranks count (a solo rank has no gang
    to lag behind); a rank must be slow in >= ``min_fraction`` of the
    counted steps where it reported, and must have reported at least
    ``STRAGGLER_MIN_STEPS`` counted steps — one noisy first step is not
    persistence."""
    k = straggler_k() if k is None else k
    recent = sorted(steps)[-window:]
    slow: Dict[int, int] = {}
    seen: Dict[int, int] = {}
    for s in recent:
        by_rank = steps[s]
        durs = {r: _duration_ms(rec) for r, rec in by_rank.items()}
        durs = {r: d for r, d in durs.items() if d is not None}
        if len(durs) < 2:
            continue
        vals = sorted(durs.values())
        median = _percentile(vals, 0.5)
        if median <= 0:
            continue
        for r, d in durs.items():
            seen[r] = seen.get(r, 0) + 1
            if d > k * median:
                slow[r] = slow.get(r, 0) + 1
    return sorted(r for r, n in slow.items()
                  if seen.get(r, 0) >= STRAGGLER_MIN_STEPS
                  and n / seen[r] >= min_fraction)


def summarize_run(steps: Dict[int, Dict[int, Dict[str, Any]]],
                  k: Optional[float] = None) -> Dict[str, Any]:
    """One run's gang summary: per-rank stats over the buffered window,
    latest-step skew, and the straggler list."""
    k = straggler_k() if k is None else k
    ranks: Dict[int, List[Dict[str, Any]]] = {}
    for by_rank in steps.values():
        for r, rec in by_rank.items():
            ranks.setdefault(r, []).append(rec)
    per_rank: Dict[int, Dict[str, Any]] = {}
    for r, recs in sorted(ranks.items()):
        durs = sorted(v for v in (_duration_ms(x) for x in recs)
                      if v is not None)
        last = max(recs, key=lambda x: x.get("step", -1))
        per_rank[r] = {
            "steps": len(recs),
            "last_step": last.get("step"),
            "mean_ms": sum(durs) / len(durs) if durs else 0.0,
            "p50_ms": _percentile(durs, 0.5),
            "p99_ms": _percentile(durs, 0.99),
            "last_total_ms": last.get("total_ms"),
            "tokens_per_sec": last.get("tokens_per_sec"),
            "mfu": last.get("mfu"),
        }
    last_step = max(steps) if steps else None
    stragglers = find_stragglers(steps, k=k)
    out: Dict[str, Any] = {
        "world": len(ranks),
        "last_step": last_step,
        "steps_buffered": len(steps),
        "per_rank": per_rank,
        "stragglers": stragglers,
        "straggler_k": k,
    }
    if last_step is not None:
        out["last_step_skew"] = step_skew(steps[last_step])
        # headline breakdown: the latest step's lowest reporting rank
        by_rank = steps[last_step]
        lead_rank = min(by_rank)
        lead = by_rank[lead_rank]
        out["last_step_breakdown"] = {
            key: lead[key] for key in
            ("data_wait_ms", "bubble_wait_ms", "compile_ms",
             "device_step_ms", "checkpoint_ms", "report_ms", "other_ms",
             "total_ms")
            if key in lead}
        # per-phase p50/p99 + trailing EMA over the lead rank's buffered
        # window — the shared step_timer.summarize_records derivation
        # (also used by the oracle validation harness and bench), so
        # train_progress consumers stop re-deriving it from raw records
        lead_recs = [steps[s][lead_rank] for s in sorted(steps)
                     if lead_rank in steps[s]]
        out["phase_summary"] = summarize_records(lead_recs)["phases"]
    return out
