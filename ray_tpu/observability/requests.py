"""Per-request flight recorder: distributed request tracing with
tail-latency attribution across the serving plane.

Every serving surface built so far is an *aggregate* — histograms,
counters, sliding windows. None of them can answer "where did THIS
request's 2 s go" or "which phase grows between p50 and p99". This
module is the per-request answer, the serving-plane sibling of the
training StepTimer:

- a :class:`RequestTrace` is minted at the gateway (or by the router
  for direct calls) under one request id, bridged to any incoming W3C
  ``traceparent`` (util/tracing.py wire format), and threaded through
  the serving path via a thread-local so every hop can stamp a phase
  without plumbing an argument through ten signatures;
- hops append **phase** records — ``qos_admission`` (gateway auth +
  QoS gate), ``queue_reserve`` (router admission/reservation),
  ``prefill``, ``kv_transfer`` (start_decode: ChunkFetcher pulls +
  adoption), ``decode_first_token``, ``decode_steady``, and the
  gateway's ``sse_flush`` (concurrent with decode, so excluded from
  the phase-sum-vs-wall invariant) — failover/preemption replays
  re-stamp the same phases tagged with their attempt number, child
  spans under the same request id;
- a completed trace lands in the process-local
  :class:`RequestTraceStore` under **tail-based retention**: every
  anomalous outcome (shed/error/deadline/disconnect/preempt/failover)
  is always kept, the slowest N are always kept, the boring majority
  is probabilistically sampled under the ``RAY_TPU_REQTRACE_*``
  budget;
- :func:`p99_attribution` diffs per-phase time between the p50 and
  p99 cohorts and names the phase that owns the tail.

One set of numbers: the store pushes stats + kept traces to the
conductor (``report_requesttrace_stats`` / ``report_requesttrace_
event``), and ``util.state.requesttrace_status()``, ``ray_tpu
requests``, ``/api/requesttrace``, the lazy ``ray_tpu_reqtrace_*``
Prometheus family, and the merged timeline's ``requests`` lane all
read the same aggregate.

Knobs (all live-retunable through util/envknobs.py):

- ``RAY_TPU_REQTRACE`` (default ``1``) — master switch; ``0`` makes
  every hook a no-op.
- ``RAY_TPU_REQTRACE_SLOWEST`` (default ``32``) — the slowest-N set
  retention always protects.
- ``RAY_TPU_REQTRACE_SAMPLE`` (default ``0.05``) — keep probability
  for ok-outcome, not-slowest traces.
- ``RAY_TPU_REQTRACE_KEPT`` (default ``512``) — hard cap on kept
  full traces per process (FIFO eviction that never evicts the
  current slowest-N).
- ``RAY_TPU_REQTRACE_WINDOW`` (default ``2048``) — compact per-request
  summaries retained for p99 attribution (every completion lands here
  regardless of full-trace retention, so the cohorts are unbiased).
"""
from __future__ import annotations

import random
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

# The canonical phase order (rendering + report ordering). ``sse_flush``
# overlaps decode on the gateway's event loop, so it is excluded from
# the phase-sum ≈ wall-time invariant.
PHASES = ("qos_admission", "queue_reserve", "prefill", "kv_transfer",
          "decode_first_token", "decode_steady", "sse_flush")
CONCURRENT_PHASES = frozenset({"sse_flush"})

# Outcomes whose traces tail-based retention always keeps.
ANOMALOUS_OUTCOMES = frozenset({"shed", "error", "deadline",
                                "disconnect", "preempt"})

def enabled() -> bool:
    """Master switch (RAY_TPU_REQTRACE, default on)."""
    from ray_tpu.util import envknobs

    return envknobs.get_bool("RAY_TPU_REQTRACE", True)


def _mint_trace_id() -> str:
    return uuid.uuid4().hex  # 32 lowercase hex — W3C trace-id width


def _mint_span_id() -> str:
    return uuid.uuid4().hex[:16]


# ---------------------------------------------------------------- trace

class RequestTrace:
    """One request's mutable phase log. Thread-safe: the gateway's
    event loop (sse_flush) and its executor thread (router phases)
    append concurrently."""

    def __init__(self, request_id: str, *, source: str = "router",
                 trace_id: Optional[str] = None,
                 tenant: Optional[str] = None,
                 cls: Optional[str] = None,
                 store: Optional["RequestTraceStore"] = None,
                 t0: Optional[float] = None):
        self.request_id = str(request_id)
        self.trace_id = trace_id or _mint_trace_id()
        self.span_id = _mint_span_id()
        self.source = source
        self.tenant = tenant
        self.cls = cls
        self.start_ts = time.time()
        self._t0 = time.perf_counter() if t0 is None else t0
        self._store = store
        self._lock = threading.Lock()
        self._phases: List[Dict[str, Any]] = []
        self._open: List[Dict[str, Any]] = []  # innermost last
        self._attempt = 1
        self._preempts = 0
        self._finished: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------- identity

    def traceparent(self) -> str:
        """W3C header value carrying this trace downstream (same wire
        format as util/tracing.py Span.traceparent)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    # --------------------------------------------------------- phases

    def _now_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3

    @contextmanager
    def phase(self, name: str, **attrs: Any) -> Iterator[Dict[str, Any]]:
        """Record ``name`` spanning the with-block (exceptions still
        record the elapsed time — a failed prefill is exactly the span
        a failover breakdown needs)."""
        rec: Dict[str, Any] = {"phase": str(name),
                               "t_ms": round(self._now_ms(), 3)}
        if attrs:
            rec.update(attrs)
        with self._lock:
            rec["attempt"] = self._attempt
            self._open.append(rec)
        t0 = time.perf_counter()
        try:
            yield rec
        except BaseException as e:
            rec["error"] = type(e).__name__
            raise
        finally:
            rec["dur_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            with self._lock:
                if rec in self._open:
                    self._open.remove(rec)
                self._phases.append(rec)

    def add_phase(self, name: str, dur_ms: float, *,
                  t_ms: Optional[float] = None,
                  concurrent: bool = False, **attrs: Any) -> None:
        """Append an already-measured phase (the gateway's accumulated
        sse_flush; retroactive qos_admission). ``concurrent`` marks
        phases that overlap others and are excluded from the
        phase-sum invariant."""
        dur_ms = float(dur_ms)
        rec: Dict[str, Any] = {
            "phase": str(name),
            "t_ms": round(self._now_ms() - dur_ms
                          if t_ms is None else t_ms, 3),
            "dur_ms": round(dur_ms, 3)}
        if concurrent or name in CONCURRENT_PHASES:
            rec["concurrent"] = True
        if attrs:
            rec.update(attrs)
        with self._lock:
            rec["attempt"] = self._attempt
            self._phases.append(rec)

    def annotate(self, **attrs: Any) -> None:
        """Merge attrs into the innermost OPEN phase (ChunkFetcher
        refining the router's kv_transfer span from inside it); numeric
        values accumulate so per-pull calls sum instead of clobber."""
        with self._lock:
            if not self._open:
                return
            top = self._open[-1]
            for k, v in attrs.items():
                if isinstance(v, (int, float)) \
                        and isinstance(top.get(k), (int, float)):
                    top[k] = top[k] + v
                else:
                    top[k] = v

    def begin_attempt(self) -> int:
        """A failover replay starts: subsequent phases are child spans
        tagged with the new attempt number under the same id."""
        with self._lock:
            self._attempt += 1
            return self._attempt

    def mark_preempt(self) -> None:
        """A QoS preemption fired against this request; its replay is
        attempt-tagged like a failover but accounted separately."""
        with self._lock:
            self._preempts += 1
            self._attempt += 1

    # --------------------------------------------------------- finish

    def finish(self, outcome: str, *, cause: Optional[str] = None,
               **attrs: Any) -> Optional[Dict[str, Any]]:
        """Seal the trace and hand it to the store. Idempotent — the
        first finish wins (the gateway finishes on disconnect while the
        router thread may still be unwinding)."""
        with self._lock:
            if self._finished is not None:
                return self._finished
            total_ms = round(self._now_ms(), 3)
            phases = [dict(p) for p in self._phases]
            attempts = self._attempt
            preempts = self._preempts
            phase_ms: Dict[str, float] = {}
            for p in phases:
                phase_ms[p["phase"]] = round(
                    phase_ms.get(p["phase"], 0.0)
                    + float(p.get("dur_ms", 0.0)), 3)
            rec: Dict[str, Any] = {
                "kind": "trace",
                "request_id": self.request_id,
                "trace_id": self.trace_id,
                "source": self.source,
                "ts": self.start_ts,
                "total_ms": total_ms,
                "outcome": str(outcome),
                "attempts": attempts,
                "replayed": attempts > 1,
                "preempts": preempts,
                "phases": phases,
                "phase_ms": phase_ms,
            }
            if cause is not None:
                rec["cause"] = str(cause)
            if self.tenant is not None:
                rec["tenant"] = self.tenant
            if self.cls is not None:
                rec["class"] = self.cls
            if attrs:
                rec.update({k: v for k, v in attrs.items()
                            if v is not None})
            self._finished = rec
        if self._store is not None:
            self._store.record(rec)
        return rec

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"request_id": self.request_id,
                    "trace_id": self.trace_id,
                    "attempt": self._attempt,
                    "phases": [dict(p) for p in self._phases]}


# ------------------------------------------------------- thread-local

_tls = threading.local()


def current_trace() -> Optional[RequestTrace]:
    return getattr(_tls, "trace", None)


@contextmanager
def activate(trace: Optional[RequestTrace]) -> Iterator[None]:
    """Bind ``trace`` as the thread's current trace for the block
    (None is a no-op so call sites need no branches). The gateway
    activates inside its executor work() so the router's generate —
    and every in-process tier hop under it — sees the trace."""
    if trace is None:
        yield
        return
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    try:
        yield
    finally:
        _tls.trace = prev


@contextmanager
def phase(name: str, **attrs: Any) -> Iterator[None]:
    """Stamp a phase on the current trace; no-op without one. The ONE
    hook instrumented code calls — it never needs to know whether a
    gateway, a direct caller, or nobody is recording."""
    tr = current_trace()
    if tr is None:
        yield
        return
    with tr.phase(name, **attrs):
        yield


def annotate(**attrs: Any) -> None:
    """Merge attrs into the current trace's innermost open phase
    (no-op without a trace — the ChunkFetcher hot path pays one
    thread-local probe)."""
    tr = current_trace()
    if tr is not None:
        tr.annotate(**attrs)


def start_trace(request_id: Optional[str] = None, *,
                source: str = "router",
                traceparent: Optional[str] = None,
                tenant: Optional[str] = None,
                cls: Optional[str] = None,
                t0: Optional[float] = None) -> Optional[RequestTrace]:
    """Mint a trace bound to the process store, bridging an incoming
    W3C traceparent's trace id when one is supplied. Returns None when
    RAY_TPU_REQTRACE=0 — every downstream hook tolerates None."""
    if not enabled():
        return None
    trace_id = None
    if traceparent:
        from ray_tpu.util import tracing

        parsed = tracing._parse_traceparent(traceparent)
        if parsed:
            trace_id = parsed["trace_id"]
    return RequestTrace(request_id or f"req-{uuid.uuid4().hex[:24]}",
                        source=source, trace_id=trace_id, tenant=tenant,
                        cls=cls, store=store(), t0=t0)


# -------------------------------------------------------- attribution

def p99_attribution(summaries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Diff per-phase mean time between the p50 cohort (total latency
    at or below the median) and the p99 cohort (at or above the 99th
    percentile; always at least the slowest request) and name the
    phase that owns the tail. Pure over compact summaries
    ({total_ms, phase_ms}) so the conductor can run it over merged
    per-component windows."""
    rows = [s for s in summaries
            if isinstance(s.get("total_ms"), (int, float))]
    if not rows:
        return {"n": 0, "phases": {}, "tail_owner": None}
    rows = sorted(rows, key=lambda s: s["total_ms"])
    n = len(rows)
    p50_cut = rows[(n - 1) // 2]["total_ms"]
    p99_cut = rows[min(n - 1, max(0, int(0.99 * n)))]["total_ms"]
    p50 = [s for s in rows if s["total_ms"] <= p50_cut]
    p99 = [s for s in rows if s["total_ms"] >= p99_cut] or [rows[-1]]

    def _mean(cohort: List[Dict[str, Any]], ph: str) -> float:
        return sum(float((s.get("phase_ms") or {}).get(ph, 0.0))
                   for s in cohort) / len(cohort)

    names: List[str] = list(PHASES)
    for s in rows:
        for ph in (s.get("phase_ms") or {}):
            if ph not in names:
                names.append(ph)
    phases: Dict[str, Dict[str, float]] = {}
    for ph in names:
        lo, hi = _mean(p50, ph), _mean(p99, ph)
        if lo == 0.0 and hi == 0.0:
            continue
        phases[ph] = {"p50_ms": round(lo, 3), "p99_ms": round(hi, 3),
                      "delta_ms": round(hi - lo, 3)}
    tail_owner = None
    deltas = {ph: v["delta_ms"] for ph, v in phases.items()}
    if deltas:
        tail_owner = max(deltas, key=lambda ph: deltas[ph])
        if deltas[tail_owner] <= 0.0:
            tail_owner = None
    out: Dict[str, Any] = {
        "n": n,
        "p50_cohort": len(p50),
        "p99_cohort": len(p99),
        "p50_total_ms": round(float(p50_cut), 3),
        "p99_total_ms": round(float(p99_cut), 3),
        "phases": phases,
        "tail_owner": tail_owner,
    }
    if tail_owner is not None:
        gap = sum(d for d in deltas.values() if d > 0)
        out["tail_share"] = round(deltas[tail_owner] / gap, 4) \
            if gap > 0 else 0.0
    return out


# ------------------------------------------------------------ metrics

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def reqtrace_metrics() -> Dict[str, Any]:
    """Lazy ray_tpu_reqtrace_* family (the repo's lazy-Prometheus
    pattern: built on first touch, rebound once fully constructed)."""
    global _metrics
    if _metrics is not None:
        return _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            m = {
                "phase_ms": Histogram(
                    "ray_tpu_reqtrace_phase_ms",
                    "Per-request phase latency by phase name (ms)",
                    boundaries=[1.0, 5.0, 10.0, 25.0, 50.0, 100.0,
                                250.0, 500.0, 1000.0, 2500.0, 5000.0,
                                10000.0],
                    tag_keys=("phase",)),
                "requests": Counter(
                    "ray_tpu_reqtrace_requests_total",
                    "Traced requests by outcome",
                    tag_keys=("outcome",)),
                "kept": Counter(
                    "ray_tpu_reqtrace_kept_total",
                    "Traces retained, by retention reason",
                    tag_keys=("reason",)),
                "dropped": Counter(
                    "ray_tpu_reqtrace_dropped_total",
                    "Completed traces not retained (sampled out)"),
                # the slowest-request exemplar: one series per CHAMPION
                # id, written only when the slowest request changes —
                # bounded by champion turnover, not request volume
                # (util/metrics.py has no series removal)
                "slowest_ms": Gauge(
                    "ray_tpu_reqtrace_slowest_ms",
                    "Slowest traced request (exemplar id in the "
                    "request_id label)",
                    tag_keys=("request_id",)),
            }
            _metrics = m
    return _metrics


# -------------------------------------------------------- conductor IO

def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker


def _notify(method: str, *args: Any) -> None:
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify(method, *args)
    except Exception:  # noqa: BLE001 — telemetry only
        pass


def push_remote_phase(request_id: str, phase_name: str,
                      dur_ms: float, *, attempt: int = 1,
                      **attrs: Any) -> None:
    """A tier hop running in ANOTHER process (actor-mode prefill or
    decode replica) records a child phase under the originating
    request id by pushing it to the conductor; ``get_request_trace``
    merges these into the kept trace's breakdown."""
    if not enabled():
        return
    ev: Dict[str, Any] = {"kind": "phase", "request_id": str(request_id),
                          "phase": str(phase_name),
                          "dur_ms": round(float(dur_ms), 3),
                          "attempt": int(attempt)}
    if attrs:
        ev.update(attrs)
    _notify("report_requesttrace_event", ev)


# -------------------------------------------------------------- store

class RequestTraceStore:
    """Process-local retention + aggregation of finished traces.

    Retention ("tail-based sampling"): every anomalous outcome is kept
    at admission; the slowest N (RAY_TPU_REQTRACE_SLOWEST) are never
    evicted while they hold the title; everything else is kept with
    probability RAY_TPU_REQTRACE_SAMPLE. The kept set is hard-capped
    (RAY_TPU_REQTRACE_KEPT) with oldest-first eviction that skips the
    current slowest-N — so anomalies age out under pressure but the
    tail exemplars survive. Compact summaries of EVERY completion land
    in a separate window (RAY_TPU_REQTRACE_WINDOW) so p99 attribution
    sees the unbiased population, not just the kept traces."""

    def __init__(self, component_id: Optional[str] = None):
        self.component_id = component_id \
            or f"reqtrace-{uuid.uuid4().hex[:8]}"
        self._lock = threading.Lock()
        self._kept: Dict[str, Dict[str, Any]] = {}  # insertion-ordered
        self._summaries: List[Dict[str, Any]] = []
        self._seq = 0
        self._completed = 0
        self._dropped = 0
        self._outcomes: Dict[str, int] = {}
        self._replayed = 0
        self._preempted = 0
        self._slowest_ms = 0.0
        self._last_push = 0.0
        self._rng = random.Random()

    # ------------------------------------------------------- knobs

    @staticmethod
    def _knobs() -> Dict[str, Any]:
        from ray_tpu.util import envknobs

        return {
            "slowest": max(1, envknobs.get_int(
                "RAY_TPU_REQTRACE_SLOWEST", 32)),
            "sample": envknobs.get_float(
                "RAY_TPU_REQTRACE_SAMPLE", 0.05),
            "kept": max(1, envknobs.get_int(
                "RAY_TPU_REQTRACE_KEPT", 512)),
            "window": max(16, envknobs.get_int(
                "RAY_TPU_REQTRACE_WINDOW", 2048)),
        }

    # ------------------------------------------------------ recording

    def record(self, rec: Dict[str, Any]) -> None:
        """Ingest one finished trace record (RequestTrace.finish)."""
        knobs = self._knobs()
        outcome = str(rec.get("outcome", "ok"))
        total_ms = float(rec.get("total_ms", 0.0))
        anomalous = (outcome in ANOMALOUS_OUTCOMES
                     or bool(rec.get("replayed"))
                     or int(rec.get("preempts", 0)) > 0)
        new_champion = False
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._completed += 1
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
            if rec.get("replayed"):
                self._replayed += 1
            if int(rec.get("preempts", 0)) > 0:
                self._preempted += 1
            summary = {"seq": seq,
                       "request_id": rec.get("request_id"),
                       "total_ms": total_ms,
                       "outcome": outcome,
                       "phase_ms": dict(rec.get("phase_ms") or {})}
            self._summaries.append(summary)
            if len(self._summaries) > knobs["window"]:
                del self._summaries[
                    :len(self._summaries) - knobs["window"]]
            slow_bar = self._slow_bar_locked(knobs["slowest"])
            reason = None
            if anomalous:
                reason = "anomaly"
            elif len(self._kept) < knobs["slowest"] \
                    or total_ms >= slow_bar:
                reason = "slowest"
            elif self._rng.random() < knobs["sample"]:
                reason = "sampled"
            if reason is None:
                self._dropped += 1
            else:
                self._kept[str(rec.get("request_id"))] = dict(rec)
                self._evict_locked(knobs)
            if total_ms > self._slowest_ms:
                self._slowest_ms = total_ms
                new_champion = True
        m = reqtrace_metrics()
        m["requests"].inc(tags={"outcome": outcome})
        for ph, ms in (rec.get("phase_ms") or {}).items():
            m["phase_ms"].observe(float(ms), tags={"phase": ph})
        if reason is None:
            m["dropped"].inc()
        else:
            m["kept"].inc(tags={"reason": reason})
            # kept traces ride the conductor event log: the timeline's
            # `requests` lane and get_request_trace read them back
            _notify("report_requesttrace_event", dict(rec))
        if new_champion:
            m["slowest_ms"].set(
                total_ms,
                tags={"request_id": str(rec.get("request_id"))})
        self.publish_telemetry()

    def _slow_bar_locked(self, n: int) -> float:
        """Caller holds self._lock. The Nth-slowest kept total — a new
        trace at or past it earns slowest-N retention."""
        totals = sorted((float(r.get("total_ms", 0.0))
                         for r in self._kept.values()), reverse=True)
        return totals[n - 1] if len(totals) >= n else 0.0

    def _evict_locked(self, knobs: Dict[str, Any]) -> None:
        """Caller holds self._lock. FIFO eviction protecting the
        current slowest-N."""
        cap = knobs["kept"]
        if len(self._kept) <= cap:
            return
        protect = set(
            sorted(self._kept,
                   key=lambda rid: float(
                       self._kept[rid].get("total_ms", 0.0)),
                   reverse=True)[:knobs["slowest"]])
        for rid in list(self._kept):
            if len(self._kept) <= cap:
                break
            if rid in protect:
                continue
            del self._kept[rid]

    # -------------------------------------------------------- reading

    def seq(self) -> int:
        with self._lock:
            return self._seq

    def summaries_since(self, seq: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(s) for s in self._summaries
                    if s["seq"] > seq]

    def trace(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._kept.get(str(request_id))
            return dict(rec) if rec else None

    def slowest(self, k: Optional[int] = None) -> List[Dict[str, Any]]:
        knobs = self._knobs()
        k = knobs["slowest"] if k is None else int(k)
        with self._lock:
            recs = sorted(self._kept.values(),
                          key=lambda r: float(r.get("total_ms", 0.0)),
                          reverse=True)[:k]
            return [dict(r) for r in recs]

    def stats(self) -> Dict[str, Any]:
        knobs = self._knobs()
        with self._lock:
            summaries = [dict(s) for s in self._summaries]
            kept = len(self._kept)
            out: Dict[str, Any] = {
                "component_id": self.component_id,
                "completed": self._completed,
                "kept": kept,
                "dropped": self._dropped,
                "outcomes": dict(self._outcomes),
                "replayed_requests": self._replayed,
                "preempted_requests": self._preempted,
                "slowest_ms": round(self._slowest_ms, 3),
                "window": len(summaries),
            }
        out["slowest"] = [
            {"request_id": r.get("request_id"),
             "total_ms": r.get("total_ms"),
             "outcome": r.get("outcome"),
             "attempts": r.get("attempts"),
             "phase_ms": dict(r.get("phase_ms") or {})}
            for r in self.slowest(knobs["slowest"])]
        out["attribution"] = p99_attribution(summaries)
        # the compact window tail rides the stats push so the conductor
        # can attribute cluster-wide over every component's population
        out["recent"] = [
            {k: v for k, v in s.items() if k != "seq"}
            for s in summaries[-256:]]
        return out

    # ------------------------------------------------------ publishing

    def publish_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_push < 0.5:
                return
            self._last_push = now
        w = _worker()
        if w is None:
            return
        try:
            w.conductor.notify("report_requesttrace_stats", w.worker_id,
                               self.component_id, self.stats())
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass


# ----------------------------------------------------- global store

_store: Optional[RequestTraceStore] = None
_store_lock = threading.Lock()


def store() -> RequestTraceStore:
    """The process's shared store (gateway + router + bench record into
    one retention budget)."""
    global _store
    if _store is None:
        with _store_lock:
            if _store is None:
                _store = RequestTraceStore()
    return _store


def _reset_store_for_tests() -> None:
    global _store
    with _store_lock:
        _store = None


__all__ = ["ANOMALOUS_OUTCOMES", "CONCURRENT_PHASES", "PHASES",
           "RequestTrace", "RequestTraceStore", "activate", "annotate",
           "current_trace", "enabled", "p99_attribution", "phase",
           "push_remote_phase", "reqtrace_metrics", "start_trace",
           "store"]
