"""ray_tpu.observability: the flight recorder.

The runtime's four observability primitives — ``util.metrics``
(conductor-pushed Prometheus registry), ``util.tracing`` (W3C spans +
chrome/OTLP export), ``util.profiling`` (jax.profiler device traces) and
the dashboard/timeline CLI — answer "what is the cluster doing?". This
layer answers the ML questions a TPU runtime must answer natively:

- what is my MFU and tokens/sec?        -> ``flops`` + ``StepTimer``
- where did the step time go?           -> ``StepTimer`` phase breakdown
  (data-wait / compile / device-step / checkpoint / report)
- which host is the straggler?          -> ``gang`` (conductor-aggregated
  per-rank skew, surfaced via ``util.state.train_progress()``,
  ``/api/train`` and ``python -m ray_tpu train-status``)
- how does it all line up in time?      -> ``timeline`` (one merged
  chrome trace: driver spans, worker task events, step markers)
- what SHOULD this step have cost?      -> ``roofline`` (the step-time
  oracle: per-generation ICI/DCN link constants + the compute roofline
  turn a layout's traced collectives into a predicted step-time
  breakdown, validated against flight-recorder measurements)
"""
from .flops import (  # noqa: F401
    NOMINAL_PEAK_FLOPS,
    PEAK_FLOPS_BF16,
    attn_flops_per_token,
    compiled_flops,
    device_peak_flops,
    mfu,
    param_count,
    params_size,
    total_peak_flops,
    train_flops_per_token,
)
from .gang import find_stragglers, step_skew, summarize_run  # noqa: F401
from .roofline import (  # noqa: F401
    LINK_CONSTANTS,
    LinkConstants,
    NOMINAL_LINK_CONSTANTS,
    calibration_fit,
    device_link_constants,
    predict_builtin_layouts,
    predict_step_time,
    validate_records,
    validate_run,
)
from .step_timer import (  # noqa: F401
    PHASES,
    StepTimer,
    summarize_records,
    telemetry_enabled,
)
from .timeline import merged_chrome_trace, merged_timeline  # noqa: F401
