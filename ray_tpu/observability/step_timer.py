"""StepTimer: the flight recorder's per-step clock.

Partitions each training step's wall time into named phases —
``data_wait`` / ``compile`` / ``device_step`` / ``checkpoint`` /
``report`` — and turns the result into tokens/sec and MFU (see
``observability.flops``). ``train/session.report()`` closes the current
step automatically, so a train_fn that uses ``TrainStep`` gets compile /
device-step accounting for free and only opts into finer phases with::

    timer = ray_tpu.train.get_step_timer()
    with timer.phase("data_wait"):
        batch = next(it)

Closed step records are buffered and shipped to the conductor in batches
(``report_train_steps``), riding the same flush cadence as metric/span
batches, where the gang-wide aggregation (``observability.gang``) builds
per-rank skew and straggler views.

Telemetry-off cost: a disabled timer's ``phase()`` returns one shared
no-op context manager (no allocation) and every other entry point is a
single attribute check — asserted by a counter microbench in tier-1, so
the hot step path never pays for a recorder nobody is reading.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

# Indirection so tests can count clock reads (the no-op path must make
# zero of them) without monkeypatching the global time module.
_now = time.perf_counter

# bubble_wait: blocked on a pipeline channel waiting for an upstream
# stage's activation / downstream stage's gradient (ray_tpu.mpmd) — the
# per-stage pipeline bubble, distinct from data_wait (input pipeline).
PHASES = ("data_wait", "bubble_wait", "compile", "device_step",
          "checkpoint", "report")

_FLUSH_EVERY = 16          # records per conductor batch
_FLUSH_INTERVAL_S = 2.0    # matches the metric/span flush cadence
_PENDING_CAP = 4096        # clusterless runs keep only this many records


def telemetry_enabled() -> bool:
    """Step telemetry defaults ON; RAY_TPU_STEP_TELEMETRY=0 disables."""
    return os.environ.get("RAY_TPU_STEP_TELEMETRY", "1") != "0"


def percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an ascending list — THE percentile
    of the flight-recorder stack (gang aggregation, summarize_records,
    the oracle validation harness all share it)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


_EMA_ALPHA = 0.3  # trailing EMA weight of the newest step


def summarize_records(records, ema_alpha: float = _EMA_ALPHA
                      ) -> Dict[str, Any]:
    """Per-phase summary over a window of step records (the StepTimer
    record schema: ``<phase>_ms`` keys plus ``other_ms``/``total_ms``):
    mean / p50 / p99 plus a trailing EMA in record order — the ONE
    derivation shared by the oracle validation harness, the conductor's
    train_progress aggregation, and bench.py, instead of each
    re-deriving stats from raw records."""
    phases: Dict[str, Dict[str, float]] = {}
    for name in (*PHASES, "other", "total"):
        key = f"{name}_ms"
        vals = [float(r[key]) for r in records
                if isinstance(r.get(key), (int, float))]
        if not vals:
            continue
        ordered = sorted(vals)
        ema = vals[0]
        for v in vals[1:]:
            ema = ema_alpha * v + (1.0 - ema_alpha) * ema
        phases[name] = {
            "mean_ms": sum(vals) / len(vals),
            "p50_ms": percentile(ordered, 0.5),
            "p99_ms": percentile(ordered, 0.99),
            "ema_ms": ema,
            "last_ms": vals[-1],
        }
    return {"steps": len(records), "phases": phases}


class _NoopCM:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP_CM = _NoopCM()


class _PhaseCM:
    __slots__ = ("_timer", "_name", "_t0")

    def __init__(self, timer: "StepTimer", name: str):
        self._timer = timer
        self._name = name

    def __enter__(self):
        self._timer.ensure_step_open()
        self._t0 = _now()
        return self

    def __exit__(self, *exc):
        self._timer.record(self._name, _now() - self._t0)
        return False


class StepTimer:
    """Per-rank step clock; one instance per training session."""

    def __init__(self, run_id: str = "", rank: int = 0,
                 world_size: int = 1, enabled: Optional[bool] = None):
        self.run_id = run_id or "default"
        self.rank = rank
        self.world_size = world_size
        self.enabled = telemetry_enabled() if enabled is None else enabled
        self._step_index = 0
        self._step_start: Optional[float] = None
        self._step_start_wall: Optional[float] = None
        self._acc: Dict[str, float] = {}
        self._pending: List[Dict[str, Any]] = []
        self._last_flush = 0.0
        # MFU inputs, usually filled in by TrainStep at first execution
        self.tokens_per_step: Optional[int] = None
        self.flops_per_step: Optional[float] = None
        self.peak_flops_total: Optional[float] = None

    # ------------------------------------------------------------- phases

    def phase(self, name: str):
        """Context manager accumulating wall time into phase `name`."""
        if not self.enabled:
            return _NOOP_CM
        return _PhaseCM(self, name)

    def record(self, name: str, seconds: float) -> None:
        """Directly account `seconds` to phase `name` in the open step.
        Recording into a not-yet-open step backdates the step start by
        `seconds` — the work clearly happened inside it."""
        if not self.enabled:
            return
        if self._step_start is None:
            self._begin_step()
            self._step_start -= seconds
            self._step_start_wall -= seconds
        self._acc[name] = self._acc.get(name, 0.0) + seconds

    def ensure_step_open(self) -> None:
        """Start the step clock now if no step is open (phase entry)."""
        if self.enabled and self._step_start is None:
            self._begin_step()

    def _begin_step(self) -> None:
        self._step_start = _now()
        self._step_start_wall = time.time()
        self._acc = {}

    # -------------------------------------------------------- MFU inputs

    def set_tokens_per_step(self, n: int) -> None:
        if self.enabled:
            self.tokens_per_step = int(n)

    def set_flops_per_step(self, f: Optional[float]) -> None:
        if self.enabled and f:
            self.flops_per_step = float(f)

    def set_peak_flops(self, f: Optional[float]) -> None:
        if self.enabled and f:
            self.peak_flops_total = float(f)

    # ------------------------------------------------------- step closing

    def end_step(self) -> Optional[Dict[str, Any]]:
        """Close the open step and return its record (None when disabled
        or nothing was recorded). Called by train.session.report()."""
        if not self.enabled or self._step_start is None:
            return None
        now, wall = _now(), time.time()
        total_s = now - self._step_start
        rec: Dict[str, Any] = {
            "step": self._step_index,
            "rank": self.rank,
            "t_start": self._step_start_wall,
            "t_end": wall,
            "total_ms": total_s * 1e3,
        }
        accounted = 0.0
        for name in PHASES:
            s = self._acc.get(name, 0.0)
            accounted += s
            rec[f"{name}_ms"] = s * 1e3
        rec["other_ms"] = max(0.0, total_s - accounted) * 1e3
        if self.tokens_per_step:
            rec["tokens"] = self.tokens_per_step
            rec["tokens_per_sec"] = self.tokens_per_step / max(total_s, 1e-9)
        # MFU against device time when we have it (total time includes
        # data wait, which is goodput, not device utilization)
        from . import flops as _flops

        device_s = self._acc.get("device_step", 0.0) or total_s
        m = _flops.mfu(self.flops_per_step, device_s, self.peak_flops_total)
        if m is not None:
            rec["mfu"] = m
        self._step_index += 1
        self._step_start = None
        self._step_start_wall = None
        self._acc = {}
        self._pending.append(rec)
        if len(self._pending) >= _FLUSH_EVERY or \
                now - self._last_flush > _FLUSH_INTERVAL_S:
            self.flush()
        return rec

    # ------------------------------------------------------------- flush

    def flush(self) -> None:
        """Ship pending records to the conductor (best-effort: a driver
        without a cluster keeps records local for direct inspection)."""
        if not self._pending:
            return
        self._last_flush = _now()
        batch, self._pending = self._pending, []
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            # no cluster: keep a bounded tail for local readers — a long
            # clusterless run (spmd trainer without ray_tpu.init) must
            # not accumulate one dict per step forever
            self._pending = batch[-_PENDING_CAP:]
            return
        try:
            w.conductor.notify("report_train_steps", self.run_id,
                               self.rank, batch)
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass

    def close(self) -> None:
        """Session teardown: flush the record tail. A partially-open
        step (e.g. the report-phase stub the last report() left behind)
        is dropped, not closed — a teardown-length pseudo-step would
        poison the gang's mean/p99 stats."""
        self._step_start = None
        self._acc = {}
        self.flush()
