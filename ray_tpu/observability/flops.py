"""FLOPs accounting: the numerator and denominator of MFU.

MFU (model FLOPs utilization) is the headline comparison metric of the
Gemma-on-TPU technical report (PAPERS.md): achieved model FLOP/s over the
chip generation's peak. This module provides both sides:

- numerator: analytic ``6·N`` training FLOPs per token for the model
  families in ``ray_tpu.models`` (plus the attention score/value term the
  6N rule misses), or the exact per-execution FLOPs XLA reports through
  ``Compiled.cost_analysis()`` when available;
- denominator: a per-generation bf16 peak-FLOPs table (public spec
  sheets), with a documented nominal constant for non-TPU backends so
  off-silicon test runs still produce a meaningful (relative) number.
"""
from __future__ import annotations

from typing import Any, Optional

# bf16 peak FLOP/s per chip by device kind (public spec sheets). The
# longest-prefix match wins so "TPU v5 lite" resolves before "TPU v5".
PEAK_FLOPS_BF16 = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "TPU v6e": 918e12,
}

# Nominal peaks for non-TPU backends: MFU off-silicon is only meaningful
# as a relative series (regression tracking in tier-1 / CI), so the
# constants just need to be stable and documented, not precise.
NOMINAL_PEAK_FLOPS = {
    "cpu": 5e11,
    "gpu": 312e12,  # A100-class bf16, the reference comparison point
}

_UNKNOWN_TPU_PEAK = 275e12  # assume v4-class so MFU stays conservative


def device_peak_flops(device: Any = None) -> float:
    """bf16 peak FLOP/s of one device (jax Device or None for the first
    local device)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    for name, peak in sorted(PEAK_FLOPS_BF16.items(),
                             key=lambda kv: -len(kv[0])):
        if kind.startswith(name):
            return peak
    platform = getattr(device, "platform", "") or ""
    if platform == "tpu":
        return _UNKNOWN_TPU_PEAK
    return NOMINAL_PEAK_FLOPS.get(platform, NOMINAL_PEAK_FLOPS["cpu"])


def total_peak_flops(devices) -> float:
    """Aggregate bf16 peak over a device collection (e.g. mesh.devices)."""
    import numpy as np

    flat = np.asarray(devices).reshape(-1)
    return float(sum(device_peak_flops(d) for d in flat))


# ------------------------------------------------------------- analytic 6N

def param_count(cfg: Any) -> int:
    """Analytic parameter count for a ``ray_tpu.models`` config
    (GPT2Config / LlamaConfig / MoEConfig). For MoE this is the ACTIVE
    parameter count (top_k experts), which is what the 6N rule wants."""
    name = type(cfg).__name__
    if name == "GPT2Config":
        return (cfg.padded_vocab * cfg.d_model          # wte (tied head)
                + cfg.max_seq_len * cfg.d_model         # wpe
                + cfg.num_layers * 12 * cfg.d_model * cfg.d_model)
    if name in ("LlamaConfig", "MoEConfig"):
        d, L = cfg.d_model, cfg.num_layers
        kv_dim = cfg.num_kv_heads * cfg.head_dim
        attn = d * d + 2 * d * kv_dim + d * d           # q, kv, o
        if name == "MoEConfig":
            mlp = cfg.top_k * 3 * d * cfg.d_ff          # active experts
        else:
            mlp = 3 * d * cfg.d_ff                      # gate/up/down
        return cfg.padded_vocab * d + L * (attn + mlp)
    raise TypeError(f"no analytic parameter count for {name}; pass a "
                    "ray_tpu.models config or use params_size()")


def params_size(params: Any) -> int:
    """Parameter count of an actual pytree (model-agnostic fallback —
    counts TOTAL parameters, so MoE models overcount vs. active)."""
    import jax

    return int(sum(x.size for x in jax.tree.leaves(params)
                   if hasattr(x, "size")))


def attn_flops_per_token(cfg: Any, seq: Optional[int] = None,
                         causal: bool = True) -> float:
    """Attention score/value FLOPs per token the 6N rule misses:
    2 matmuls (QK^T, PV) x 2·d·T each, fwd+bwd = 3x, halved causal."""
    seq = seq or cfg.max_seq_len
    per = 12.0 * cfg.num_layers * cfg.d_model * seq
    return per / 2 if causal else per


def train_flops_per_token(cfg: Any, seq: Optional[int] = None,
                          causal: bool = True) -> float:
    """Training (fwd+bwd) FLOPs per token: 6·N plus the attention term."""
    return 6.0 * param_count(cfg) + attn_flops_per_token(cfg, seq, causal)


# ------------------------------------------------------ XLA cost analysis

def compiled_flops(compiled: Any) -> Optional[float]:
    """Per-execution FLOPs from an XLA ``Compiled.cost_analysis()``, or
    None when the backend doesn't report them. Normalizes the two
    historical return shapes (dict vs. list-of-dicts)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend without cost analysis
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    try:
        flops = float(cost.get("flops", 0.0))
    except (AttributeError, TypeError, ValueError):
        return None
    return flops if flops > 0 else None


def mfu(flops_per_step: Optional[float], step_seconds: float,
        peak_flops_total: Optional[float]) -> Optional[float]:
    """Achieved / peak model FLOP/s, or None when either side is unknown."""
    if not flops_per_step or not peak_flops_total or step_seconds <= 0:
        return None
    return flops_per_step / step_seconds / peak_flops_total
