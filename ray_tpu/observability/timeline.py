"""One unified timeline: driver/worker spans + conductor task events +
training step markers merged into a single chrome-trace file, so one
Perfetto load shows driver, gang, and step structure together
(``python -m ray_tpu timeline --merged``).

The three sources already exist separately — ``util.state.timeline``
(task events), ``util.tracing.to_chrome_trace`` (spans), and the flight
recorder's step records (``report_train_steps``) — this module only
merges and labels them:

- task events:   pid = job id,            tid = executing worker
- spans:         pid = recording process, tid = trace id prefix
- step markers:  pid = "train:<run_id>",  tid = "rank <r>", one X event
                 per step carrying the phase breakdown in args, plus a
                 counter event series for tokens/sec and MFU.
- resilience:    pid = "resilience",      tid = event kind — instant
                 markers for preemptions, restarts, quarantines, grace
                 checkpoints, and chaos injections (ray_tpu.resilience),
                 plus the serving plane's recovery markers: request
                 `failover` (serve/disagg.py replaying a request off a
                 dead tier replica), replica `replace` and
                 `breaker_trip` (serve/autoscale.py self-healing) —
                 recovery events share one lane whether they heal a
                 training gang or a serving tier.
- weights:       pid = "weights",         tid = event kind — instant
                 markers for weight publishes, fetches, hot swaps, GC
                 and reaps (ray_tpu.weights), so a serving replica's
                 swap lines up against the training steps that
                 produced the version.
- kvcache:       pid = "kvcache",         tid = event kind — instant
                 markers for paged-KV prefix hits, evictions, and
                 swap invalidations (models/kvcache.py), so serving
                 cache behavior lines up against request traffic and
                 weight swaps.
- pipeline:      pid = "pipeline",        tid = "stage <s>" (or event
                 kind) — one lane per MPMD stage-gang (ray_tpu.mpmd):
                 formation, per-stage run reports (bubble fraction,
                 channel bytes), stage deaths — beside the per-stage
                 train-step markers whose args carry bubble_wait_ms.
- online:        pid = "online",          tid = the sampler id (or
                 event kind) — instant markers of the online learning
                 loop (ray_tpu.online): rollouts completing, learner
                 ingests, weight publishes and sampler hot swaps, so
                 the sampler/learner cadence reads directly against the
                 weights lane's fabric-side publish/fetch/swap markers.
- disagg:        pid = "disagg",          tid = event kind — instant
                 markers of disaggregated serving (serve/disagg.py):
                 KV publishes on the prefill tier, prefill->decode
                 KV transfers with their shm/rpc byte split, and
                 router sheds, so cross-replica KV traffic lines up
                 against request latency and the kvcache lane.
- lora:          pid = "lora",            tid = event kind — instant
                 markers of multi-tenant LoRA serving (serve/lora.py):
                 adapter page_in / evict / swap per tenant, so adapter
                 paging lines up against the disagg lane's requests
                 and the weights lane's publishes.
- gateway:       pid = "gateway",         tid = event kind — instant
                 markers of the HTTP front door (serve/gateway.py +
                 serve/qos.py): request accepts, first bytes (TTFT),
                 batch-slot preemptions, rate-limit rejections, and
                 client disconnects per priority class, so ingress
                 pressure reads against the disagg lane's shed markers
                 and the lora lane's tenant paging.
- speculation:   pid = "speculation",     tid = event kind — instant
                 markers for speculative-decoding verify outcomes
                 (models/engine.py): spec_accept / spec_reject with the
                 accepted/proposed split per verify tick. The engine
                 pushes them through the kvcache event channel (ONE
                 report path), and the merge splits the spec_* slice
                 into its own lane so acceptance reads against the
                 kvcache and gateway tracks.
- autoscale:     pid = "autoscale",       tid = event kind — instant
                 markers of the serving autoscaler (serve/autoscale.py):
                 scale_up / drain / scale_down per tier, so replica-set
                 changes line up against the disagg lane's shed markers
                 and the request traffic they react to.
- oracle:        pid = "oracle" — a predicted-step-time COUNTER track
                 (one "C" series per layout, observability.roofline)
                 that draws the analytic roofline under the measured
                 train-step markers, plus instant validation markers
                 carrying the fitted calibration and residuals.
- kvplane:       pid = "kvplane",        tid = event kind — instant
                 markers of the global KV plane (serve/kvplane.py):
                 HBM->host-arena spills, tier-2 re-adoptions, tier-3
                 prefix publishes/adoptions through the chunk fabric,
                 directory-routed requests, eviction storms, and
                 directory reaps, so cross-tier prefix movement reads
                 against the kvcache lane's block-level hits and the
                 disagg lane's transfers.
- requests:      pid = "requests",       tid = the request id prefix —
                 one REAL "X" span per recorded phase of a kept request
                 trace (observability.requests): qos_admission ->
                 queue_reserve -> prefill -> kv_transfer ->
                 decode_first_token -> decode_steady -> sse_flush, with
                 failover/preempt replay attempts suffixed " a<n>" so a
                 replayed request reads as child spans under one id,
                 plus one enclosing span carrying the outcome and total
                 — a sampled request's whole lifecycle rendered against
                 the disagg/gateway lanes that produced it.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def step_trace_events(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chrome-trace events for flattened step records (each record
    carries run_id/rank — see ConductorHandler.get_train_steps)."""
    out: List[Dict[str, Any]] = []
    for rec in records:
        t0, t1 = rec.get("t_start"), rec.get("t_end")
        if t0 is None or t1 is None:
            continue
        pid = f"train:{rec.get('run_id', 'default')}"
        tid = f"rank {rec.get('rank', 0)}"
        args = {k: round(v, 3) for k, v in rec.items()
                if k.endswith("_ms") and isinstance(v, (int, float))}
        for key in ("tokens", "tokens_per_sec", "mfu"):
            if key in rec:
                args[key] = rec[key]
        out.append({
            "name": f"step {rec.get('step', '?')}", "cat": "train_step",
            "ph": "X", "ts": t0 * 1e6,
            "dur": max(0.0, t1 - t0) * 1e6,
            "pid": pid, "tid": tid, "args": args,
        })
        # counter tracks: throughput/MFU trend lines under the steps
        counters = {}
        if rec.get("tokens_per_sec") is not None:
            counters["tokens_per_sec"] = round(rec["tokens_per_sec"], 1)
        if rec.get("mfu") is not None:
            counters["mfu_pct"] = round(100.0 * rec["mfu"], 3)
        if counters:
            out.append({"name": "throughput", "cat": "train_step",
                        "ph": "C", "ts": t1 * 1e6, "pid": pid,
                        "args": counters})
    return out


def resilience_trace_events(events: List[Dict[str, Any]]
                            ) -> List[Dict[str, Any]]:
    """Instant markers for resilience events (preemption, restart,
    quarantine, grace checkpoint, chaos injection, recovery) — one
    global-scope "i" event per entry so failures and recoveries line up
    against the task/span/step tracks they interrupted."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        # serving-plane recovery markers name their replica/router/host
        # the same way training markers name their node/run (explicit
        # None checks: a chaos kill's replica index 0 is a real label)
        where = next((ev[k] for k in ("node_id", "run_id", "name",
                                      "replica", "router", "host")
                      if ev.get(k) is not None), None)
        out.append({
            "name": f"{kind}:{where}" if where is not None else kind,
            "cat": "resilience", "ph": "i", "s": "g", "ts": ts * 1e6,
            "pid": "resilience", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def weight_trace_events(events: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Instant markers for weight-fabric events (publish, fetch, swap,
    gc, reap) — mirrors the resilience track under pid "weights"."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        name = ev.get("name")
        ver = ev.get("version")
        label = f"{kind}:{name}" if name else kind
        if ver is not None:
            label += f"@v{ver}"
        out.append({
            "name": label, "cat": "weights", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "weights", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def kvcache_trace_events(events: List[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
    """Instant markers for paged-KV cache events (prefix_hit, evict,
    invalidate) — mirrors the weights track under pid "kvcache"."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        kind = str(ev.get("kind", "event"))
        if ts is None or kind.startswith("spec_"):
            continue  # spec_* markers render on the speculation lane
        label = kind
        if ev.get("outcome"):
            label += f":{ev['outcome']}"
        if ev.get("reused_tokens") is not None:
            label += f" +{ev['reused_tokens']}tok"
        out.append({
            "name": label, "cat": "kvcache", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "kvcache", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def speculation_trace_events(events: List[Dict[str, Any]]
                             ) -> List[Dict[str, Any]]:
    """Instant markers for speculative-decoding verify outcomes — the
    spec_* slice of the kvcache event channel (engines push spec_accept
    / spec_reject through the same report_kvcache_event path), rendered
    under its own pid "speculation" so acceptance reads as a lane
    instead of noise in the prefix-cache track."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        kind = str(ev.get("kind", "event"))
        if ts is None or not kind.startswith("spec_"):
            continue
        label = kind
        if ev.get("proposed") is not None:
            label += f" {ev.get('accepted', 0)}/{ev['proposed']}"
        out.append({
            "name": label, "cat": "speculation", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "speculation", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def pipeline_trace_events(events: List[Dict[str, Any]]
                          ) -> List[Dict[str, Any]]:
    """Instant markers for MPMD pipeline events (open, stage_registered,
    formed, stage_report, stage_death, closed) — one lane per stage
    under pid "pipeline" so each stage-gang's lifecycle reads as its own
    track."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        name = ev.get("pipeline")
        stage = ev.get("stage")
        label = f"{kind}:{name}" if name else kind
        if stage is not None:
            label += f"/stage{stage}"
        out.append({
            "name": label, "cat": "pipeline", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "pipeline",
            "tid": f"stage {stage}" if stage is not None else kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def online_trace_events(events: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Instant markers for online-loop events (rollout, ingest,
    publish, swap) — one lane per sampler (learner events lane under
    their kind) beneath pid "online"."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        label = kind
        if ev.get("sampler"):
            label += f":{ev['sampler']}"
        if ev.get("weights_version") is not None:
            label += f"@v{ev['weights_version']}"
        elif ev.get("version") is not None:
            label += f"@v{ev['version']}"
        out.append({
            "name": label, "cat": "online", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "online",
            "tid": str(ev.get("sampler") or kind),
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def disagg_trace_events(events: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Instant markers for disaggregated-serving events (kv_publish,
    kv_transfer, shed) — mirrors the kvcache track under pid
    "disagg"."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        label = kind
        where = ev.get("server") or ev.get("router")
        if where:
            label += f":{where}"
        if ev.get("bytes") is not None:
            label += f" {ev['bytes']}B"
        out.append({
            "name": label, "cat": "disagg", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "disagg", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def lora_trace_events(events: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """Instant markers for multi-tenant LoRA events (page_in, evict,
    swap) — mirrors the kvcache track under pid "lora", so adapter
    paging lines up against the disagg lane's request markers and the
    weights lane's publish markers."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        label = kind
        if ev.get("tenant"):
            label += f":{ev['tenant']}"
        if ev.get("version") is not None:
            label += f"@v{ev['version']}"
        if ev.get("bytes") is not None:
            label += f" {ev['bytes']}B"
        out.append({
            "name": label, "cat": "lora", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "lora", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def kvplane_trace_events(events: List[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
    """Instant markers for global-KV-plane events (spill, tier2_hit,
    tier3_publish, tier3_adopt, directory_hit, evict_storm, reap) —
    mirrors the kvcache track under pid "kvplane", so tier demotions
    and cross-replica adoptions read against the engines' block-level
    reuse markers and the disagg lane's transfer markers."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        label = kind
        where = ev.get("replica") or ev.get("holder") or \
            ev.get("router")
        if where:
            label += f":{where}"
        if ev.get("blocks") is not None:
            label += f" {ev['blocks']}blk"
        if ev.get("nbytes") is not None:
            label += f" {ev['nbytes']}B"
        out.append({
            "name": label, "cat": "kvplane", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "kvplane", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def gateway_trace_events(events: List[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
    """Instant markers for HTTP front-door events (accept, first_byte,
    preempt, rate_limit, disconnect) — mirrors the disagg track under
    pid "gateway", so ingress pressure and preemptions read against
    the router's shed/transfer markers and the lora lane's tenant
    paging."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        label = kind
        if ev.get("class"):
            label += f":{ev['class']}"
        if ev.get("tenant"):
            label += f"@{ev['tenant']}"
        if ev.get("ttft_ms") is not None:
            label += f" {ev['ttft_ms']}ms"
        out.append({
            "name": label, "cat": "gateway", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "gateway", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def autoscale_trace_events(events: List[Dict[str, Any]]
                           ) -> List[Dict[str, Any]]:
    """Instant markers for serving-autoscaler events (scale_up, drain,
    scale_down) — mirrors the disagg track under pid "autoscale" so
    replica-set changes read against the shed/transfer markers they
    react to."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        label = kind
        if ev.get("tier"):
            label += f":{ev['tier']}"
        if ev.get("to") is not None:
            label += f"->{ev['to']}"
        out.append({
            "name": label, "cat": "autoscale", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "autoscale", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def oracle_trace_events(events: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """The step-time oracle's track (observability.roofline): every
    prediction event becomes a point on a per-layout ``predicted_step_ms``
    counter series under pid "oracle" (the analytic roofline drawn under
    the measured train-step markers); validation events become instant
    markers carrying calibration + residuals."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        ts = ev.get("ts")
        if ts is None:
            continue
        kind = str(ev.get("kind", "event"))
        layout = ev.get("layout")
        if kind == "prediction":
            pred = ev.get("predicted_step_ms")
            if pred is None:
                continue
            out.append({
                "name": f"predicted_step_ms:{layout}" if layout
                else "predicted_step_ms",
                "cat": "oracle", "ph": "C", "ts": ts * 1e6,
                "pid": "oracle",
                "args": {"predicted_step_ms": round(float(pred), 3)},
            })
            continue
        label = kind
        if layout:
            label += f":{layout}"
        cal = ev.get("calibration")
        if cal is not None:
            label += f" cal={float(cal):.2f}"
        out.append({
            "name": label, "cat": "oracle", "ph": "i", "s": "g",
            "ts": ts * 1e6, "pid": "oracle", "tid": kind,
            "args": {k: v for k, v in ev.items()
                     if k != "ts" and v is not None},
        })
    return out


def requests_trace_events(events: List[Dict[str, Any]]
                          ) -> List[Dict[str, Any]]:
    """Real spans for kept request traces (observability.requests):
    each ``kind == "trace"`` event carries its phase list with offsets
    from the request's start, so every phase renders as an "X" span on
    the request's own track — replay attempts (failover/preempt) get an
    " a<n>" suffix so they read as child spans under the one request id.
    One enclosing span per request carries the outcome and totals."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("kind") != "trace":
            continue
        ts = float(ev.get("ts", 0.0))
        rid = str(ev.get("request_id", "?"))
        tid = rid[:12]
        total_ms = float(ev.get("total_ms", 0.0) or 0.0)
        # the conductor stamps ts at completion; phases carry offsets
        # from the request's start, so anchor the lane at ts - total
        t_start = ts - total_ms / 1e3
        out.append({
            "name": f"request {ev.get('outcome', '?')}",
            "cat": "request", "ph": "X", "ts": t_start * 1e6,
            "dur": max(0.0, total_ms) * 1e3,
            "pid": "requests", "tid": tid,
            "args": {"request_id": rid,
                     "outcome": ev.get("outcome"),
                     "attempts": ev.get("attempts", 1),
                     "preempts": ev.get("preempts", 0),
                     "total_ms": round(total_ms, 3)},
        })
        for ph in ev.get("phases", []) or []:
            name = str(ph.get("phase", "phase"))
            attempt = int(ph.get("attempt", 1) or 1)
            if attempt > 1:
                name += f" a{attempt}"
            dur_ms = float(ph.get("dur_ms", 0.0) or 0.0)
            t_ms = ph.get("t_ms")
            t0 = t_start + (float(t_ms) / 1e3 if t_ms is not None
                            else 0.0)
            args = {k: v for k, v in ph.items()
                    if k not in ("phase", "t_ms") and v is not None}
            out.append({
                "name": name, "cat": "request_phase", "ph": "X",
                "ts": t0 * 1e6, "dur": max(0.0, dur_ms) * 1e3,
                "pid": "requests", "tid": tid, "args": args,
            })
    return out


def task_trace_events(task_events: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """Chrome-trace events for conductor task events — the ONE rendering
    of the task-event schema, shared by the plain `util.state.timeline`
    export and the merged flight-recorder trace."""
    out: List[Dict[str, Any]] = []
    for ev in task_events:
        worker = ev.get("worker")
        out.append({
            "name": ev["name"], "cat": "task", "ph": "X",
            "ts": ev["start"] * 1e6,
            "dur": max(0.0, ev["end"] - ev["start"]) * 1e6,
            "pid": ev.get("job_id", "job"),
            "tid": f"{worker[0]}:{worker[1]}" if worker else "driver",
            "args": {"task_id": ev["task_id"],
                     "status": ev.get("status", "FINISHED")},
        })
    return out


def merged_chrome_trace(task_events: List[Dict[str, Any]],
                        spans: List[Dict[str, Any]],
                        step_records: List[Dict[str, Any]],
                        resilience_events: Optional[
                            List[Dict[str, Any]]] = None,
                        weight_events: Optional[
                            List[Dict[str, Any]]] = None,
                        kvcache_events: Optional[
                            List[Dict[str, Any]]] = None,
                        pipeline_events: Optional[
                            List[Dict[str, Any]]] = None,
                        online_events: Optional[
                            List[Dict[str, Any]]] = None,
                        disagg_events: Optional[
                            List[Dict[str, Any]]] = None,
                        oracle_events: Optional[
                            List[Dict[str, Any]]] = None,
                        autoscale_events: Optional[
                            List[Dict[str, Any]]] = None,
                        lora_events: Optional[
                            List[Dict[str, Any]]] = None,
                        gateway_events: Optional[
                            List[Dict[str, Any]]] = None,
                        requesttrace_events: Optional[
                            List[Dict[str, Any]]] = None,
                        kvplane_events: Optional[
                            List[Dict[str, Any]]] = None
                        ) -> List[Dict[str, Any]]:
    """Merge the sources into one sorted event list."""
    from ray_tpu.util import tracing

    trace = task_trace_events(task_events)
    trace.extend(tracing.to_chrome_trace(spans))
    trace.extend(step_trace_events(step_records))
    if resilience_events:
        trace.extend(resilience_trace_events(resilience_events))
    if weight_events:
        trace.extend(weight_trace_events(weight_events))
    if kvcache_events:
        trace.extend(kvcache_trace_events(kvcache_events))
        trace.extend(speculation_trace_events(kvcache_events))
    if pipeline_events:
        trace.extend(pipeline_trace_events(pipeline_events))
    if online_events:
        trace.extend(online_trace_events(online_events))
    if disagg_events:
        trace.extend(disagg_trace_events(disagg_events))
    if oracle_events:
        trace.extend(oracle_trace_events(oracle_events))
    if autoscale_events:
        trace.extend(autoscale_trace_events(autoscale_events))
    if lora_events:
        trace.extend(lora_trace_events(lora_events))
    if gateway_events:
        trace.extend(gateway_trace_events(gateway_events))
    if requesttrace_events:
        trace.extend(requests_trace_events(requesttrace_events))
    if kvplane_events:
        trace.extend(kvplane_trace_events(kvplane_events))
    trace.sort(key=lambda e: e.get("ts", 0.0))
    return trace


def merged_timeline(filename: Optional[str] = None,
                    limit: int = 10_000) -> List[Dict[str, Any]]:
    """Pull all sources from the live cluster and merge (the
    ``timeline --merged`` backend). Flushes this process's pending task
    events and spans first so a short driver's trace is complete."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    w._flush_task_events()  # spans ride the same flush (tracing.drain)
    events = w.conductor.call("get_task_events", limit, timeout=30.0)
    spans = w.conductor.call("get_spans", limit, timeout=30.0)
    try:
        steps = w.conductor.call("get_train_steps", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-flight-recorder conductor
        steps = []
    try:
        resil = w.conductor.call("get_resilience_events", limit,
                                 timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-resilience conductor
        resil = []
    try:
        wev = w.conductor.call("get_weight_events", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-weights conductor
        wev = []
    try:
        kvev = w.conductor.call("get_kvcache_events", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-kvcache conductor
        kvev = []
    try:
        pev = w.conductor.call("get_pipeline_events", limit,
                               timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-mpmd conductor
        pev = []
    try:
        oev = w.conductor.call("get_online_events", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-online conductor
        oev = []
    try:
        dev = w.conductor.call("get_disagg_events", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-disagg conductor
        dev = []
    try:
        orev = w.conductor.call("get_oracle_events", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-oracle conductor
        orev = []
    try:
        asev = w.conductor.call("get_autoscale_events", limit,
                                timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-autoscale conductor
        asev = []
    try:
        lev = w.conductor.call("get_lora_events", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-lora conductor
        lev = []
    try:
        gev = w.conductor.call("get_gateway_events", limit, timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-gateway conductor
        gev = []
    try:
        rtev = w.conductor.call("get_requesttrace_events", limit,
                                timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-requesttrace conductor
        rtev = []
    try:
        kpev = w.conductor.call("get_kvplane_events", limit,
                                timeout=30.0)
    except Exception:  # noqa: BLE001 — pre-kvplane conductor
        kpev = []
    trace = merged_chrome_trace(events, spans, steps, resil, wev, kvev,
                                pev, oev, dev, orev, asev, lev, gev,
                                rtev, kpev)
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
