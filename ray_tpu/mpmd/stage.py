"""One MPMD pipeline stage: its own separately-compiled program on its
own slice-gang.

``StageProgram`` is the per-stage ``TrainStep`` analog: it owns the
stage's params + optimizer state and jit-compiles the stage's OWN
forward, backward (vjp recompute), and — on the last stage — fused
loss-and-grad programs, independently of every other stage. That
independence is the point of MPMD (arXiv 2412.14374): no stage ever
traces another stage's computation, so per-stage compile is O(stage) and
stages may be heterogeneous.

``run_stage`` executes the stage's schedule ticks against the
activation/gradient channels, accumulating gradients per microbatch and
applying one optimizer update per pipeline step — blocked-on-channel
time is accounted to the flight recorder's ``bubble_wait`` phase, so
the merged timeline shows each stage's bubble directly.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from .channels import ActivationChannel
from .metrics import pipeline_metrics
from .schedule import FORWARD, stage_schedule


class StageProgram:
    """Compiled programs + state for one stage.

    apply_fn(params, x) -> y is this stage's forward. The LAST stage
    additionally owns loss_fn(y, target) -> scalar and compiles one
    fused loss-and-grad program instead of a separate forward/backward
    pair (in 1F1B its backward follows its forward immediately)."""

    def __init__(self, apply_fn: Callable, params: Any, optimizer,
                 *, loss_fn: Optional[Callable] = None,
                 is_last: bool = False,
                 needs_input_grad: bool = True,
                 num_microbatches: int = 1):
        import jax

        self.apply_fn = apply_fn
        self.params = params
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.is_last = bool(is_last)
        self.needs_input_grad = bool(needs_input_grad)
        self.num_microbatches = int(num_microbatches)
        if self.is_last and loss_fn is None:
            raise ValueError("the last stage needs a loss_fn")
        self.opt_state = optimizer.init(params)
        self._saved: Dict[int, Any] = {}  # mb -> forward input
        self._grad_acc: Any = None

        self._fwd = jax.jit(apply_fn)

        if self.needs_input_grad:
            def bwd(params, x, dy):
                _y, vjp = jax.vjp(apply_fn, params, x)
                return vjp(dy)
        else:
            # stage 0 has no upstream: dropping dL/dx INSIDE the jit
            # lets XLA dead-code-eliminate the whole input-grad chain
            def bwd(params, x, dy):
                _y, vjp = jax.vjp(apply_fn, params, x)
                gp, _gx = vjp(dy)
                return gp, None

        self._bwd = jax.jit(bwd)

        if self.is_last:
            def loss_and_grad(params, x, target):
                def of(p, xx):
                    return loss_fn(apply_fn(p, xx), target)

                (loss, gx_fn) = jax.value_and_grad(of, argnums=(0, 1))(
                    params, x)
                return loss, gx_fn

            self._last = jax.jit(loss_and_grad)

        # gradient-accumulation sum and the per-step update, jitted so
        # the whole stage step stays on-device
        def add(acc, g):
            return jax.tree.map(lambda a, b: a + b, acc, g)

        self._add = jax.jit(add)

        def update(params, opt_state, acc):
            import optax

            grads = jax.tree.map(
                lambda g: g / float(self.num_microbatches), acc)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
            return optax.apply_updates(params, updates), opt_state

        self._update = jax.jit(update)

    # ------------------------------------------------------------- ticks

    def forward(self, mb: int, x: Any) -> Any:
        """Run this stage's forward on microbatch `mb`, saving the
        input for the backward pass. Last-stage forwards only save (its
        loss-and-grad program recomputes the forward with the target in
        hand)."""
        self._saved[mb] = x
        if self.is_last:
            return None
        return self._fwd(self.params, x)

    def backward(self, mb: int, dy: Any = None,
                 target: Any = None) -> Any:
        """Run the backward for microbatch `mb`. Mid/first stages take
        the downstream gradient `dy`; the last stage takes `target` and
        returns (loss, upstream_grad); others return upstream_grad.
        Accumulates this stage's param grads."""
        x = self._saved.pop(mb)
        if self.is_last:
            loss, (gp, gx) = self._last(self.params, x, target)
        else:
            loss = None
            gp, gx = self._bwd(self.params, x, dy)
        self._grad_acc = gp if self._grad_acc is None \
            else self._add(self._grad_acc, gp)
        return (loss, gx) if self.is_last else gx

    def apply_update(self) -> None:
        """One optimizer step from the accumulated microbatch grads."""
        if self._grad_acc is None:
            raise RuntimeError("apply_update before any backward")
        self.params, self.opt_state = self._update(
            self.params, self.opt_state, self._grad_acc)
        self._grad_acc = None

    def reset_step_state(self) -> None:
        """Drop partial per-step state (saved activations, accumulated
        grads). Called at run start so a retry on a still-live actor —
        after an aborted run raised mid-step — can never average a dead
        step's partial gradient sums into its first update."""
        self._saved.clear()
        self._grad_acc = None

    @property
    def live_activations(self) -> int:
        return len(self._saved)


def run_stage(program: StageProgram, *, name: str, stage: int,
              num_stages: int, schedule: str, num_microbatches: int,
              num_steps: int, data_fn: Callable[[int], Any],
              timer=None, recv_timeout: float = 60.0,
              run_id: str = "",
              poll_interval: float = 0.25) -> Dict[str, Any]:
    """Drive one stage through `num_steps` pipeline steps.

    data_fn(step) -> (x, target): the deterministic per-step batch
    source every stage shares (stage 0 consumes x, the last stage
    consumes target; mid stages call it for neither). Returns the stage
    summary (losses on the last stage, channel stats, bubble fraction).
    """
    import contextlib

    import jax
    import numpy as np

    def bubble_cm():
        return (timer.phase("bubble_wait") if timer is not None
                else contextlib.nullcontext())

    s, last = int(stage), int(stage) == int(num_stages) - 1
    program.reset_step_state()  # a retried run must start clean
    ticks = stage_schedule(schedule, s, num_stages, num_microbatches)
    in_ch = out_ch = gin_ch = gout_ch = None
    if s > 0:
        in_ch = ActivationChannel(name, s - 1, s, stage=s,
                                  run_id=run_id,
                                  poll_interval=poll_interval)
        gout_ch = ActivationChannel(name, s, s - 1, stage=s,
                                    run_id=run_id,
                                    poll_interval=poll_interval)
    if not last:
        out_ch = ActivationChannel(name, s, s + 1, stage=s,
                                   run_id=run_id,
                                   poll_interval=poll_interval)
        gin_ch = ActivationChannel(name, s + 1, s, stage=s,
                                   run_id=run_id,
                                   poll_interval=poll_interval)

    losses: List[float] = []
    bubble_fracs: List[float] = []

    def prefetch_next(idx: int, step: int) -> None:
        """Start the NEXT recv-needing tick's channel pull now, so it
        streams during this tick's compute (channels.prefetch — the
        bubble_wait shrinker; within-step only, the sender may not
        exist across a step boundary yet)."""
        for t in ticks[idx + 1:]:
            if t.op == FORWARD:
                if s > 0:
                    in_ch.prefetch(step, t.mb, "act",
                                   timeout=recv_timeout)
                    return
            elif not last:
                gin_ch.prefetch(step, t.mb, "grad",
                                timeout=recv_timeout)
                return
    # first execution of each jitted program traces+compiles and is
    # attributed to the compile phase; every later call (including the
    # rest of step 0's microbatches) is device_step
    compiled = {"fwd": False, "bwd": False}

    def compute_phase(kind: str) -> str:
        if compiled[kind]:
            return "device_step"
        compiled[kind] = True
        return "compile"

    t_run0 = time.perf_counter()
    try:
        for step in range(int(num_steps)):
            t_step0 = time.perf_counter()
            bubble_s = 0.0
            micro_x = micro_t = None
            if s == 0 or last:
                x_full, t_full = data_fn(step)
                if s == 0:
                    micro_x = _split_microbatches(x_full,
                                                  num_microbatches)
                if last:
                    micro_t = _split_microbatches(t_full,
                                                  num_microbatches)
            step_losses: List[Any] = []
            for tick_idx, tick in enumerate(ticks):
                if tick.op == FORWARD:
                    if s == 0:
                        x = jax.tree.map(lambda a: a[tick.mb], micro_x)
                    else:
                        t0 = time.perf_counter()
                        with bubble_cm():
                            x = in_ch.recv(step, tick.mb, "act",
                                           timeout=recv_timeout)
                        bubble_s += time.perf_counter() - t0
                        prefetch_next(tick_idx, step)
                    t0 = time.perf_counter()
                    y = program.forward(tick.mb, x)
                    if timer is not None:
                        # last-stage forwards only save (no program
                        # ran), so they never consume the compile slot
                        timer.record(
                            compute_phase("fwd") if not last
                            else "device_step",
                            time.perf_counter() - t0)
                    if out_ch is not None:
                        out_ch.send(step, tick.mb, "act", y)
                else:
                    if last:
                        tgt = jax.tree.map(lambda a: a[tick.mb], micro_t)
                        t0 = time.perf_counter()
                        loss, gx = program.backward(tick.mb,
                                                    target=tgt)
                        if timer is not None:
                            timer.record(compute_phase("bwd"),
                                         time.perf_counter() - t0)
                        step_losses.append(loss)
                    else:
                        t0 = time.perf_counter()
                        with bubble_cm():
                            dy = gin_ch.recv(step, tick.mb, "grad",
                                             timeout=recv_timeout)
                        bubble_s += time.perf_counter() - t0
                        prefetch_next(tick_idx, step)
                        t0 = time.perf_counter()
                        gx = program.backward(tick.mb, dy=dy)
                        if timer is not None:
                            timer.record(compute_phase("bwd"),
                                         time.perf_counter() - t0)
                    if gout_ch is not None:
                        gout_ch.send(step, tick.mb, "grad", gx)
            t0 = time.perf_counter()
            program.apply_update()
            if timer is not None:
                timer.record("device_step", time.perf_counter() - t0)
            step_s = time.perf_counter() - t_step0
            frac = min(1.0, bubble_s / step_s) if step_s > 0 else 0.0
            bubble_fracs.append(frac)
            pipeline_metrics()["bubble_fraction"].set(
                frac, tags={"pipeline": name, "stage": str(s)})
            if last and step_losses:
                losses.append(float(np.mean(
                    [float(v) for v in step_losses])))
            if timer is not None:
                timer.end_step()
        # success path: wait for the neighbors to TAKE the final
        # step's payloads before close() drops the chunk refs (the
        # refs are the chunks' lifetime — closing right after the last
        # send would race the store free against the last fetch)
        for ch in (out_ch, gout_ch):
            if ch is not None:
                ch.drain(timeout=max(10.0, recv_timeout / 2))
    finally:
        for ch in (in_ch, out_ch, gin_ch, gout_ch):
            if ch is not None:
                ch.close()
    chans = [c for c in (in_ch, out_ch, gin_ch, gout_ch)
             if c is not None]
    summary: Dict[str, Any] = {
        "stage": s,
        "run_id": run_id,  # generation fencing at report time
        "steps": int(num_steps),
        "ticks_per_step": len(ticks),
        "losses": losses,
        "bubble_fraction": (sum(bubble_fracs) / len(bubble_fracs)
                            if bubble_fracs else 0.0),
        "last_bubble_fraction": (bubble_fracs[-1] if bubble_fracs
                                 else 0.0),
        "sent_bytes": sum(c.stats.sent_bytes for c in chans),
        "recv_bytes": sum(c.stats.recv_bytes for c in chans),
        "sent_msgs": sum(c.stats.sent_msgs for c in chans),
        "recv_msgs": sum(c.stats.recv_msgs for c in chans),
        "prefetch_hits": sum(c.stats.prefetch_hits for c in chans),
        "channel_wait_s": sum(c.stats.wait_s for c in chans),
        "elapsed_s": time.perf_counter() - t_run0,
    }
    return summary


def _split_microbatches(batch: Any, m: int) -> Any:
    """Reshape every leaf [B, ...] -> [m, B/m, ...]; validates
    divisibility with the batch named."""
    import jax
    import numpy as np

    def split(a):
        a = np.asarray(a)
        if a.shape[0] % m != 0:
            raise ValueError(
                f"batch {a.shape[0]} not divisible by "
                f"num_microbatches {m}")
        return a.reshape(m, a.shape[0] // m, *a.shape[1:])

    return jax.tree.map(split, batch)


class StageActor:
    """The stage-gang member actor (wrapped with ray_tpu.remote by the
    PipelineConductor). One actor per stage host; rank 0 of each stage
    registers the stage with the conductor's pipeline registry."""

    def __init__(self, name: str, stage: int, num_stages: int, *,
                 schedule: str, num_microbatches: int,
                 slice_id: Optional[int] = None, run_id: str = ""):
        self.name = name
        self.stage = int(stage)
        self.num_stages = int(num_stages)
        self.schedule = schedule
        self.num_microbatches = int(num_microbatches)
        self.slice_id = self.stage if slice_id is None else int(slice_id)
        self.run_id = run_id or f"mpmd/{name}"
        self._program: Optional[StageProgram] = None

    def setup(self, apply_fn: Callable, init_params: Any, optimizer,
              loss_fn: Optional[Callable] = None) -> Dict[str, Any]:
        """Build this stage's own program (independent compile) and
        register the stage-gang with the conductor. Returns the
        registration result ({"formed": bool, ...})."""
        import os

        from ray_tpu._private import worker as worker_mod

        self._program = StageProgram(
            apply_fn, init_params, optimizer, loss_fn=loss_fn,
            is_last=self.stage == self.num_stages - 1,
            needs_input_grad=self.stage > 0,
            num_microbatches=self.num_microbatches)
        w = worker_mod.global_worker
        info = {"worker_id": getattr(w, "worker_id", None),
                "slice_id": self.slice_id,
                "run_id": self.run_id,
                "pid": os.getpid()}
        return w.conductor.call("pipeline_register_stage", self.name,
                                self.stage, info, timeout=30.0)

    def run_steps(self, num_steps: int, data_fn: Callable[[int], Any],
                  recv_timeout: float = 60.0) -> Dict[str, Any]:
        """Execute `num_steps` pipeline steps of this stage's schedule
        and report the stage summary to every surface (registry stats,
        step telemetry, Prometheus, timeline marker)."""
        from ray_tpu._private import worker as worker_mod
        from ray_tpu.observability.step_timer import StepTimer
        from ray_tpu.util import metrics as metrics_mod

        if self._program is None:
            raise RuntimeError("setup() must run before run_steps()")
        timer = StepTimer(self.run_id, rank=self.stage,
                          world_size=self.num_stages)
        try:
            summary = run_stage(
                self._program, name=self.name, stage=self.stage,
                num_stages=self.num_stages, schedule=self.schedule,
                num_microbatches=self.num_microbatches,
                num_steps=num_steps, data_fn=data_fn, timer=timer,
                recv_timeout=recv_timeout, run_id=self.run_id)
        finally:
            timer.close()
        w = worker_mod.global_worker
        # the registry copy must stay O(1) per stage: the full per-step
        # loss list rides the run_steps return value to the driver, not
        # every status payload — only the final loss goes to the record
        reg_stats = {k: v for k, v in summary.items() if k != "losses"}
        if summary.get("losses"):
            reg_stats["last_loss"] = summary["losses"][-1]
        try:
            w.conductor.call("report_pipeline_stats", self.name,
                             self.stage, reg_stats, timeout=10.0)
            w.conductor.notify("report_pipeline_event", {
                "kind": "stage_report", "pipeline": self.name,
                "stage": self.stage, "steps": summary["steps"],
                "bubble_fraction": round(summary["bubble_fraction"], 6),
                "sent_bytes": summary["sent_bytes"],
                "recv_bytes": summary["recv_bytes"]})
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        metrics_mod.flush()
        return summary

    def get_params(self) -> Any:
        """This stage's current params (host copies) — test/debug."""
        import jax
        import numpy as np

        return jax.tree.map(np.asarray, self._program.params)
