"""PipelineConductor: forms the stage-gangs and supervises the run.

One stage per slice: each stage of the pipeline is its own gang (one
actor process per stage today; ``hosts_per_stage != 1`` — multi-host
stage-gangs with jax.distributed inside one stage — is the
ROADMAP-named follow-up and refused loudly) assigned a slice identity,
so a stage shares a failure domain with nothing but itself. Formation reuses the
conductor-KV rendezvous machinery the SPMD gangs use —
``pipeline_register_stage`` commits the pipeline "formed" atomically
when the LAST stage registers, exactly like the weight registry's
fragment commit — and the run rides the resilience layer's
``GangSupervisor``: one dead stage kills the survivors (their channel
recvs can never complete) so the driver's ``get`` fails fast instead of
waiting out a channel timeout.

Each stage compiles its own program (``StageProgram``) in its own
process; the conductor never sees a trace of any stage's computation —
only registry metadata, channel descriptors, and telemetry.
"""
from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.util.runtime import require_worker

from .schedule import SCHEDULES, bubble_fraction
from .stage import StageActor


def _detect_num_slices(default: int) -> int:
    """Slice count for stage placement: the virtual-slice override
    (off-silicon dev/test path, parallel.multislice) wins; otherwise
    assume one slice per stage."""
    from ray_tpu.parallel.multislice import VIRTUAL_SLICES_ENV

    v = os.environ.get(VIRTUAL_SLICES_ENV)
    if v:
        try:
            return max(1, int(v))
        except ValueError:
            pass
    return default


class PipelineConductor:
    """Forms and drives one named MPMD pipeline.

    stage_fns[i](params_i, x) -> y is stage i's forward;
    stage_params[i] its initial params. The last stage owns
    loss_fn(y_last, target) -> scalar. `optimizer` (an optax
    GradientTransformation) is instantiated independently per stage.
    """

    def __init__(self, name: str,
                 stage_fns: Sequence[Callable],
                 stage_params: Sequence[Any],
                 optimizer,
                 loss_fn: Callable, *,
                 num_microbatches: int,
                 schedule: str = "1f1b",
                 hosts_per_stage: int = 1,
                 resources_per_stage: Optional[Dict[str, float]] = None,
                 run_id: str = ""):
        if len(stage_fns) != len(stage_params):
            raise ValueError(
                f"{len(stage_fns)} stage fns but "
                f"{len(stage_params)} stage param trees")
        if len(stage_fns) < 2:
            raise ValueError("an MPMD pipeline needs >= 2 stages; use "
                             "JaxTrainer/TrainStep for a single program")
        if schedule not in SCHEDULES:
            raise ValueError(f"unknown schedule {schedule!r}; "
                             f"one of {sorted(SCHEDULES)}")
        if int(hosts_per_stage) != 1:
            # multi-host stage-gangs (jax.distributed inside one stage)
            # are the ROADMAP-named follow-up; refusing beats silently
            # spawning a single-process stage for an 8-host request
            raise NotImplementedError(
                f"hosts_per_stage={hosts_per_stage}: stage-gangs run "
                "one host per stage today (multi-host stage-gangs are "
                "a ROADMAP follow-up)")
        self.name = name
        self.stage_fns = list(stage_fns)
        self.stage_params = list(stage_params)
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.num_stages = len(stage_fns)
        self.num_microbatches = int(num_microbatches)
        self.schedule = schedule
        self.hosts_per_stage = int(hosts_per_stage)
        self.resources_per_stage = dict(resources_per_stage
                                        or {"CPU": 1.0})
        self.run_id = run_id or f"mpmd/{name}/{uuid.uuid4().hex[:8]}"
        self.bubble_estimate = bubble_fraction(
            schedule, self.num_stages, self.num_microbatches)
        self._worker = require_worker("forming a pipeline")
        self._actors: List[Any] = []
        self._pg = None

    # ----------------------------------------------------------- formation

    def form(self, timeout: float = 120.0) -> Dict[str, Any]:
        """Open the registry entry, spawn one stage-gang per slice, and
        block until every stage registered (the atomic "formed" commit).
        Lints the schedule first — a >20% analytic bubble is a warning
        naming the M >= 4*S rule, same policy as TrainStep's spec lint."""
        import warnings

        from ray_tpu.analysis import check_pipeline_schedule, errors, \
            format_report

        findings = check_pipeline_schedule(
            self.num_stages, self.num_microbatches, self.schedule,
            where=f"pipeline/{self.name}")
        if errors(findings):  # defensive: the rule never errors today
            raise ValueError(format_report(findings))
        if findings and any(f.severity == "warning" for f in findings):
            warnings.warn("shardlint: " + format_report(findings),
                          stacklevel=2)

        w = self._worker
        res = w.conductor.call(
            "pipeline_open", self.name,
            {"num_stages": self.num_stages,
             "schedule": self.schedule,
             "num_microbatches": self.num_microbatches,
             "bubble_estimate": self.bubble_estimate,
             "run_id": self.run_id}, timeout=30.0)
        if isinstance(res, dict) and res.get("error"):
            raise RuntimeError(f"pipeline_open rejected: {res['error']}")

        try:
            return self._form_gangs(timeout)
        except BaseException:
            # any formation failure (remote setup raised, registration
            # rejected, poll timeout) must not leak live stage actors,
            # the placement group, or a forever-"forming" registry
            # entry — GangSupervisor only covers actor DEATH
            self.close()
            raise

    def _form_gangs(self, timeout: float) -> Dict[str, Any]:
        import ray_tpu
        from ray_tpu.util.placement_group import placement_group

        w = self._worker
        num_slices = _detect_num_slices(self.num_stages)
        remote_cls = ray_tpu.remote(StageActor)
        opts = {"num_cpus": self.resources_per_stage.get("CPU", 1.0)}
        extra = {k: v for k, v in self.resources_per_stage.items()
                 if k != "CPU"}
        if extra:
            opts["resources"] = extra
        # one bundle per stage, SPREAD: stages land on distinct hosts
        # whenever capacity allows, so a stage really does share a
        # failure domain with nothing but itself (soft on a dev box,
        # where one node hosts every bundle)
        self._pg = placement_group(
            [dict(self.resources_per_stage)
             for _ in range(self.num_stages)], strategy="SPREAD")
        self._pg.wait()
        opts["placement_group"] = self._pg
        self._actors = [
            remote_cls.options(**opts).remote(
                self.name, s, self.num_stages,
                schedule=self.schedule,
                num_microbatches=self.num_microbatches,
                slice_id=s % num_slices, run_id=self.run_id)
            for s in range(self.num_stages)]
        setup_refs = [
            a.setup.remote(
                self.stage_fns[s], self.stage_params[s], self.optimizer,
                self.loss_fn if s == self.num_stages - 1 else None)
            for s, a in enumerate(self._actors)]
        from ray_tpu.resilience import GangSupervisor

        with GangSupervisor(self._actors, run_id=self.run_id):
            registrations = ray_tpu.get(setup_refs)
        rejected = [r for r in registrations
                    if isinstance(r, dict) and r.get("error")]
        if rejected:
            # a rejected registration (wrong generation, closed
            # pipeline) would otherwise burn the whole formation
            # timeout before surfacing as a generic TimeoutError
            raise RuntimeError(
                f"pipeline {self.name!r} stage registration rejected: "
                f"{rejected[0]['error']}")
        # the LAST registration flips formed=True atomically; poll only
        # as the safety net for out-of-order notify delivery
        deadline = time.monotonic() + timeout
        while True:
            rec = w.conductor.call("pipeline_get", self.name,
                                   timeout=10.0)
            if rec and rec.get("formed"):
                return rec
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"pipeline {self.name!r} did not form within "
                    f"{timeout}s: "
                    f"{len((rec or {}).get('stages') or {})}/"
                    f"{self.num_stages} stages registered")
            time.sleep(0.05)

    # ----------------------------------------------------------------- run

    def run(self, num_steps: int, data_fn: Callable[[int], Any],
            recv_timeout: float = 60.0) -> Dict[str, Any]:
        """Drive `num_steps` pipeline steps across all stage-gangs under
        gang supervision. Returns {"losses": [...], "stages": [summary
        per stage]}; losses come from the last stage."""
        import ray_tpu
        from ray_tpu.resilience import GangSupervisor

        if not self._actors:
            self.form()
        refs = [a.run_steps.remote(num_steps, data_fn,
                                   recv_timeout=recv_timeout)
                for a in self._actors]
        try:
            with GangSupervisor(self._actors, run_id=self.run_id):
                summaries = ray_tpu.get(refs)
        except Exception as e:
            # the supervisor already killed the survivors (their
            # channel recvs could never complete); mark the pipeline
            # lane so the timeline shows WHY the run stopped
            try:
                self._worker.conductor.notify("report_pipeline_event", {
                    "kind": "stage_death", "pipeline": self.name,
                    "detail": f"{type(e).__name__}: {e}"[:500]})
            except Exception:  # noqa: BLE001 — telemetry only
                pass
            raise
        return {"losses": summaries[-1].get("losses", []),
                "stages": summaries}

    def stage_params_snapshot(self) -> List[Any]:
        """Host copies of every stage's current params (test/debug)."""
        import ray_tpu

        return ray_tpu.get([a.get_params.remote()
                            for a in self._actors])

    # --------------------------------------------------------------- close

    def close(self) -> None:
        """Kill the stage-gangs, release their placement group, and
        close the registry entry."""
        import ray_tpu

        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        self._actors = []
        if self._pg is not None:
            from ray_tpu.util.placement_group import \
                remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001 — conductor mid-shutdown
                pass
            self._pg = None
        try:
            self._worker.conductor.call("pipeline_close", self.name,
                                        timeout=10.0)
        except Exception:  # noqa: BLE001 — conductor mid-shutdown
            pass

    def __enter__(self) -> "PipelineConductor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["PipelineConductor"]
