"""Pipeline schedules: per-stage tick order for MPMD execution.

A schedule is, per stage, the exact sequence of forward/backward
microbatch ticks that stage executes; cross-stage ordering is enforced
by the activation/gradient channels (a tick blocks until its input
arrives), so these functions only need to emit a LOCALLY correct order
that is globally deadlock-free.

Two schedules (arXiv 2412.14374 §3; Megatron-LM's terminology):

- ``gpipe``: fill-drain — all M forwards, then all M backwards. Peak
  activation memory is O(M) per stage; bubble fraction (S-1)/(M+S-1).
- ``1f1b``: warm-up of (S-1-s) forwards on stage s, then steady-state
  strict 1F/1B alternation, then cool-down backwards. Same warm-up
  bubble as GPipe, but peak activation memory is O(S) — independent of
  M — which is what lets M grow to amortize the bubble.

``bubble_fraction`` is the analytic estimate shardlint reports
(`analysis` rule ``pipeline-bubble``): both schedules idle each stage
for S-1 of the M+S-1 tick slots, so keep M >= 4*S to stay under ~20%
(the rule `parallel/pipeline.py` documents).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

FORWARD = "F"
BACKWARD = "B"


@dataclass(frozen=True)
class Tick:
    """One unit of stage work: op is FORWARD or BACKWARD, mb the
    microbatch index."""

    op: str
    mb: int

    def __str__(self) -> str:
        return f"{self.op}{self.mb}"


def _validate(num_stages: int, num_microbatches: int) -> Tuple[int, int]:
    s, m = int(num_stages), int(num_microbatches)
    if s < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if m < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}")
    return s, m


def gpipe_schedule(stage: int, num_stages: int,
                   num_microbatches: int) -> List[Tick]:
    """Fill-drain: every forward, then every backward (same order on
    every stage; the channels impose the S-1 tick stagger)."""
    _s, m = _validate(num_stages, num_microbatches)
    return ([Tick(FORWARD, i) for i in range(m)]
            + [Tick(BACKWARD, i) for i in range(m)])


def one_f_one_b_schedule(stage: int, num_stages: int,
                         num_microbatches: int) -> List[Tick]:
    """Non-interleaved 1F1B for stage `stage` (0-based): warm-up of
    ``min(M, S-1-stage)`` forwards, steady-state 1F/1B alternation,
    cool-down backwards. The last stage has no warm-up — it alternates
    from the first microbatch, which is what bounds live activations at
    O(S) per stage."""
    s, m = _validate(num_stages, num_microbatches)
    warmup = min(m, s - 1 - int(stage))
    ticks: List[Tick] = [Tick(FORWARD, i) for i in range(warmup)]
    fwd, bwd = warmup, 0
    while bwd < m:
        if fwd < m:
            ticks.append(Tick(FORWARD, fwd))
            fwd += 1
        ticks.append(Tick(BACKWARD, bwd))
        bwd += 1
    return ticks


SCHEDULES = {"gpipe": gpipe_schedule, "1f1b": one_f_one_b_schedule}


def stage_schedule(schedule: str, stage: int, num_stages: int,
                   num_microbatches: int) -> List[Tick]:
    """The tick list stage `stage` executes under `schedule`."""
    try:
        fn = SCHEDULES[schedule]
    except KeyError:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r}; "
            f"one of {sorted(SCHEDULES)}") from None
    return fn(stage, num_stages, num_microbatches)


def max_live_activations(schedule: str, stage: int, num_stages: int,
                         num_microbatches: int) -> int:
    """Peak number of saved forward activations on `stage` (the memory
    argument for 1F1B): forwards minus backwards, maximized over the
    tick sequence."""
    live = peak = 0
    for t in stage_schedule(schedule, stage, num_stages,
                            num_microbatches):
        live += 1 if t.op == FORWARD else -1
        peak = max(peak, live)
    return peak


def bubble_fraction(schedule: str, num_stages: int,
                    num_microbatches: int) -> float:
    """Analytic pipeline-bubble estimate: idle fraction of each stage's
    timeline. Delegates to the ONE implementation shardlint reports
    from (analysis.pipelines rule ``pipeline-bubble``): (S-1)/(M+S-1)
    for GPipe's fill-drain and the identical warm-up + cool-down bubble
    for non-interleaved 1F1B (1F1B saves memory, not bubble)."""
    from ray_tpu.analysis.pipelines import estimate_bubble_fraction

    s, m = _validate(num_stages, num_microbatches)
    return estimate_bubble_fraction(schedule, s, m)


def validate_dependencies(schedules: Dict[int, List[Tick]],
                          num_stages: int, num_microbatches: int) -> None:
    """Assert the per-stage tick lists are globally deadlock-free under
    channel semantics (test helper): simulate all stages, advancing any
    stage whose next tick's inputs are available, and require every
    tick to complete.

    Input availability: F(mb) on stage s needs F(mb) done on s-1;
    B(mb) on stage s needs F(mb) done on s AND B(mb) done on s+1."""
    done = {(s, t.op, t.mb): False
            for s, ticks in schedules.items() for t in ticks}
    pos = {s: 0 for s in schedules}

    def ready(s: int, t: Tick) -> bool:
        if t.op == FORWARD:
            return s == 0 or done.get((s - 1, FORWARD, t.mb), False)
        if not done.get((s, FORWARD, t.mb), False):
            return False
        return s == num_stages - 1 or \
            done.get((s + 1, BACKWARD, t.mb), False)

    progressed = True
    while progressed:
        progressed = False
        for s, ticks in schedules.items():
            while pos[s] < len(ticks) and ready(s, ticks[pos[s]]):
                done[(s, ticks[pos[s]].op, ticks[pos[s]].mb)] = True
                pos[s] += 1
                progressed = True
    stuck = {s: str(ticks[pos[s]]) for s, ticks in schedules.items()
             if pos[s] < len(ticks)}
    if stuck:
        raise AssertionError(f"schedule deadlocks at {stuck}")


__all__ = ["BACKWARD", "FORWARD", "SCHEDULES", "Tick", "bubble_fraction",
           "gpipe_schedule", "max_live_activations",
           "one_f_one_b_schedule", "stage_schedule",
           "validate_dependencies"]
