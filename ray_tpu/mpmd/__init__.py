"""ray_tpu.mpmd — multi-program (MPMD) pipeline parallelism across
slices.

Where ``parallel.pipeline`` runs a GPipe schedule *inside one jit
program* over the SPMD ``pp`` mesh axis (every stage shares one compiled
program and one failure domain), this package runs each stage as its OWN
program on its own slice-gang (arXiv 2412.14374): the
:class:`PipelineConductor` forms one stage-gang per slice through the
conductor-KV rendezvous, every stage compiles its own forward/backward
independently, and microbatch activations/gradients stream
point-to-point between adjacent stages over the object plane's chunked
transfer (``util.chunks`` — the weight fabric's no-gather path).
``schedule`` drives the ticks: 1F1B (warm-up, steady 1F/1B alternation,
cool-down) by default, GPipe fill-drain as the fallback.

Unlocks what single-program pipelining cannot express: models larger
than one slice's program, independent per-stage compilation, and
heterogeneous stages.

Surfaces (the full convention): ``util.state.pipeline_status()``,
``ray_tpu pipeline`` CLI, dashboard ``/api/pipeline``, Prometheus
``ray_tpu_pipeline_bubble_fraction`` /
``ray_tpu_pipeline_activations_bytes_total``, per-stage ``bubble_wait``
in the flight recorder, and a ``pipeline`` lane of instant markers in
the merged timeline.
"""
from .channels import ActivationChannel, ChannelStats  # noqa: F401
from .conductor import PipelineConductor  # noqa: F401
from .schedule import (  # noqa: F401
    SCHEDULES,
    Tick,
    bubble_fraction,
    gpipe_schedule,
    one_f_one_b_schedule,
    stage_schedule,
)
from .trainer import PipelineTrainer  # noqa: F401
