"""Activation/gradient channels: point-to-point microbatch transfer
between adjacent stage-gangs.

A channel is unidirectional (one sender stage, one receiver stage) and
moves pytrees of host arrays through the object plane's shared chunked
transfer (``util.chunks`` — the weight fabric's 64MB-chunked no-gather
path, one implementation for both subsystems). Sends require an OPEN
pipeline registry entry (``pipeline_open``): a closed or GC-evicted
generation's sends fail fast instead of leaking undeliverable entries
toward the conductor's mailbox cap. The payload never rides
the control plane: ``send`` puts every leaf into the SENDER's own object
store and registers only a metadata descriptor in the conductor's
channel mailbox; ``recv`` takes the descriptor and pulls the chunks
directly from the sender's store (shm zero-copy on the same host,
64MB-ranged streaming across hosts/DCN). Same no-full-copy invariant as
the weights: no process other than sender and receiver ever holds the
bytes, and the conductor holds none at all.

Ownership: the sender's ObjectRefs ARE the chunks' lifetime. A slot
(mb, kind) is retained for the current and previous pipeline step —
schedule dependencies guarantee the receiver consumed a slot before the
sender can produce it twice more — so per-stage channel memory is
bounded at 2*M live microbatch tensors regardless of run length.

Wakeup rides the `pipeline` pubsub channel with a bounded poll as the
safety net (a conductor restart drops subscriptions), mirroring
WeightSubscriber.wait_for_version.

``prefetch(step, mb, kind)`` starts the pull in the background so the
next microbatch's chunks stream WHILE the stage computes the current
one (the ``WeightSync(prefetch=True)`` shape) — ``run_stage`` issues it
right after each recv, shrinking ``bubble_wait`` to the residual wait;
``stats.prefetch_hits`` counts recvs served this way and the
no-full-copy accounting is unchanged (the prefetch's fetcher is adopted
by the recv, so every chunk still crosses the plane exactly once).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util import chunks
from ray_tpu.util.runtime import pipeline_run_token as run_token
from ray_tpu.util.runtime import require_worker

from .metrics import pipeline_metrics


@dataclass
class ChannelStats:
    """Accounting for one endpoint (send and/or recv side)."""

    sent_msgs: int = 0
    sent_chunks: int = 0
    sent_bytes: int = 0
    recv_msgs: int = 0
    recv_chunks: int = 0
    recv_bytes: int = 0
    # chunks that crossed the object plane vs. served from the local
    # store (same-host stages) — the no-full-copy accounting: bytes
    # moved == payload bytes, exactly once per chunk
    fetched_remote_chunks: int = 0
    fetched_remote_bytes: int = 0
    max_fetch_bytes: int = 0
    wait_s: float = 0.0  # cumulative blocked-in-recv (bubble) time
    # recvs served by a prefetch issued during stage compute (the
    # WeightSync(prefetch=True) shape): their fetch overlapped compute,
    # so only the residual wait — not the whole transfer — is bubble
    prefetch_hits: int = 0
    per_kind: Dict[str, int] = field(default_factory=dict)


class ActivationChannel:
    """One directed edge of the pipeline graph: stage `src` -> `dst` of
    pipeline `name`. ``kind`` distinguishes payload streams sharing the
    edge ("act" forward activations, "grad" backward gradients travel
    the REVERSED edge via their own channel instance)."""

    def __init__(self, name: str, src: int, dst: int, *,
                 stage: Optional[int] = None,
                 run_id: str = "",
                 poll_interval: float = 0.25,
                 worker=None):
        self.name = name
        self.src = int(src)
        self.dst = int(dst)
        # which stage this endpoint belongs to (metrics tag); defaults
        # to the sender for send-side use
        self.stage = self.src if stage is None else int(stage)
        # run_id scopes the keys to ONE pipeline generation: after a
        # driver restart reopens the name, an orphaned old stage's
        # sends can never be delivered to the new generation's recvs
        # (their keys differ), on top of pipeline_open's mailbox purge.
        # "/" is the key separator, so the run token flattens it
        # (run_token() — the conductor's put fencing parses it back).
        self._prefix = (f"{name}/ch/{run_token(run_id)}/"
                        f"{self.src}->{self.dst}")
        self._worker = worker or require_worker(
            "using pipeline channels")
        self._poll = max(0.001, float(poll_interval))
        self.stats = ChannelStats()
        # (step, mb, kind) -> chunk refs; holding them IS the chunks'
        # lifetime (see module docstring for the retention window)
        self._held: Dict[Tuple[int, int, str], List[Any]] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        # (step, mb, kind) -> in-flight prefetch record; recv() drains
        # it instead of polling the mailbox itself
        self._prefetched: Dict[Tuple[int, int, str],
                               Dict[str, Any]] = {}
        self._closed = False
        self._worker.subscribe_channel("pipeline", self._on_msg)

    # ------------------------------------------------------------- pubsub

    def _on_msg(self, msg: Any) -> None:
        """Pure wakeup: the mailbox take below stays the source of
        truth for what is actually deliverable."""
        if isinstance(msg, dict) and msg.get("kind") == "channel_put" \
                and str(msg.get("key", "")).startswith(self._prefix):
            with self._cv:
                self._cv.notify_all()

    def _key(self, step: int, mb: int, kind: str) -> str:
        return f"{self._prefix}/{int(step)}/{int(mb)}/{kind}"

    # --------------------------------------------------------------- send

    def send(self, step: int, mb: int, kind: str, tree: Any) -> int:
        """Chunk `tree` into this process's store and register the
        descriptor with the conductor mailbox. Returns payload bytes."""
        refs, desc = chunks.put_tree(self._worker, tree)
        desc.update(step=int(step), mb=int(mb), kind=kind,
                    src=self.src, dst=self.dst, ts=time.time())
        with self._lock:
            self._held[(int(step), int(mb), kind)] = refs
            # retention window: current + previous step per slot
            pruned = [k for k in self._held if k[0] <= int(step) - 2]
            for k in pruned:
                del self._held[k]
        if pruned:
            self._discard_mailbox(pruned)
        res = self._worker.conductor.call(
            "pipeline_channel_put", self._key(step, mb, kind), desc,
            timeout=30.0)
        if isinstance(res, dict) and res.get("error"):
            with self._lock:
                self._held.pop((int(step), int(mb), kind), None)
            raise RuntimeError(
                f"pipeline channel send rejected: {res['error']}")
        nbytes = int(desc["total_bytes"])
        self.stats.sent_msgs += 1
        self.stats.sent_chunks += len(refs)
        self.stats.sent_bytes += nbytes
        self.stats.per_kind[f"sent_{kind}"] = \
            self.stats.per_kind.get(f"sent_{kind}", 0) + 1
        pipeline_metrics()["activations_bytes"].inc(
            nbytes, tags={"pipeline": self.name,
                          "stage": str(self.stage),
                          "direction": "send"})
        return nbytes

    # --------------------------------------------------------------- recv

    def _take_descriptor(self, step: int, mb: int, kind: str,
                         timeout: float) -> Dict[str, Any]:
        """Poll the mailbox until (step, mb, kind) is deliverable (the
        pubsub wakeup shortens the poll); single delivery — the caller
        owns the descriptor."""
        key = self._key(step, mb, kind)
        deadline = time.monotonic() + timeout
        while True:
            desc = self._worker.conductor.call("pipeline_channel_take",
                                               key, timeout=30.0)
            if desc is not None:
                return desc
            if self._closed:
                raise RuntimeError(
                    f"pipeline {self.name!r}: channel "
                    f"{self.src}->{self.dst} closed while waiting for "
                    f"{kind} microbatch {mb} of step {step}")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"pipeline {self.name!r}: stage {self.dst} waited "
                    f"{timeout}s for {kind} microbatch {mb} of step "
                    f"{step} from stage {self.src} — upstream stage "
                    "dead or wedged?")
            with self._cv:
                self._cv.wait(min(remaining, self._poll))

    def prefetch(self, step: int, mb: int, kind: str,
                 timeout: float = 60.0) -> None:
        """Start pulling (step, mb, kind) in the BACKGROUND so a later
        recv() finds the chunks already fetched — issued during stage
        compute, the same prefetch shape as ``WeightSync(prefetch=
        True)``: the transfer overlaps compute and only the residual
        wait lands in ``bubble_wait``. Idempotent per slot; errors
        surface at the consuming recv()."""
        key3 = (int(step), int(mb), kind)
        with self._lock:
            if self._closed or key3 in self._prefetched:
                return
            rec: Dict[str, Any] = {"done": threading.Event(),
                                   "tree": None, "fetcher": None,
                                   "desc": None, "error": None}
            self._prefetched[key3] = rec

        def pull():
            try:
                desc = self._take_descriptor(step, mb, kind, timeout)
                # record the take IMMEDIATELY: delivery is single-shot,
                # so recv() must be able to tell "descriptor consumed,
                # fetch failed" (not retryable) apart from "take timed
                # out" (retryable on recv's own budget)
                rec["desc"] = desc
                fetcher = chunks.ChunkFetcher(self._worker,
                                              caller="activations")
                rec["tree"] = chunks.fetch_tree(self._worker, desc,
                                                fetcher)
                rec["fetcher"] = fetcher
            except Exception as e:  # noqa: BLE001 — re-raised at recv
                rec["error"] = e
            finally:
                rec["done"].set()

        threading.Thread(
            target=pull, daemon=True,
            name=f"chan-prefetch-{self.src}to{self.dst}").start()

    def recv(self, step: int, mb: int, kind: str,
             timeout: float = 60.0) -> Any:
        """Block until the (step, mb, kind) payload is deliverable,
        then pull its chunks point-to-point from the sender (or adopt
        the in-flight prefetch's pull). The blocked time accumulates
        into ``stats.wait_s`` (the caller additionally times it into
        the StepTimer's ``bubble_wait`` phase)."""
        key3 = (int(step), int(mb), kind)
        with self._lock:
            pre = self._prefetched.pop(key3, None)
        t0 = time.monotonic()
        if pre is not None:
            if not pre["done"].wait(timeout):
                # still in flight: re-stash so a RETRIED recv adopts the
                # pull once it lands — dropping the record here would
                # orphan a descriptor the thread consumes moments later
                # (single delivery: no fresh take could ever succeed)
                with self._lock:
                    self._prefetched.setdefault(key3, pre)
                raise TimeoutError(
                    f"pipeline {self.name!r}: prefetch of {kind} "
                    f"microbatch {mb} of step {step} from stage "
                    f"{self.src} did not finish within {timeout}s")
            if isinstance(pre["error"], TimeoutError) \
                    and pre["desc"] is None:
                # the background take timed out against the PREFETCH
                # issuance clock WITHOUT consuming the descriptor — a
                # slow upstream may have published since, so fall back
                # to a fresh take on recv's own budget (pre-prefetch
                # behavior) instead of failing a recv that would have
                # succeeded. A fetch timeout AFTER the take (desc set)
                # is NOT retryable — delivery is single-shot — so it
                # re-raises below like any other prefetch error.
                pre = None
            elif pre["error"] is not None:
                raise pre["error"]
        if pre is not None:
            self.stats.wait_s += time.monotonic() - t0
            self.stats.prefetch_hits += 1
            desc, fetcher, tree = pre["desc"], pre["fetcher"], \
                pre["tree"]
        else:
            remaining = max(0.0, timeout - (time.monotonic() - t0))
            desc = self._take_descriptor(step, mb, kind, remaining)
            self.stats.wait_s += time.monotonic() - t0
            fetcher = chunks.ChunkFetcher(self._worker,
                                          caller="activations")
            tree = chunks.fetch_tree(self._worker, desc, fetcher)
        nbytes = int(desc["total_bytes"])
        self.stats.recv_msgs += 1
        self.stats.recv_chunks += len(desc["leaves"])
        self.stats.recv_bytes += nbytes
        self.stats.fetched_remote_chunks += fetcher.chunks_fetched
        self.stats.fetched_remote_bytes += fetcher.fetched_bytes
        self.stats.max_fetch_bytes = max(
            self.stats.max_fetch_bytes,
            max((int(e["nbytes"]) for e in desc["leaves"]), default=0))
        self.stats.per_kind[f"recv_{kind}"] = \
            self.stats.per_kind.get(f"recv_{kind}", 0) + 1
        pipeline_metrics()["activations_bytes"].inc(
            nbytes, tags={"pipeline": self.name,
                          "stage": str(self.stage),
                          "direction": "recv"})
        return tree

    # -------------------------------------------------------------- close

    def drain(self, timeout: float = 10.0) -> bool:
        """Sender-side close barrier: block until every descriptor this
        endpoint registered has been TAKEN by the receiver. The refs
        this channel holds ARE the chunks' lifetime, so close() right
        after the final send would race the store free against the
        receiver's last fetch — once the mailbox entry is taken, the
        receiver constructs its borrowing ObjectRef within the free
        grace window and the chunks are safe to drop. Returns False on
        timeout (receiver dead; the caller closes anyway)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                keys = [self._key(s, mb, k) for (s, mb, k)
                        in self._held]
            if not keys:
                return True
            pending = self._worker.conductor.call(
                "pipeline_channel_pending", keys, timeout=30.0)
            if not pending:
                return True
            if time.monotonic() > deadline:
                return False
            with self._cv:
                self._cv.wait(self._poll)

    def held_slots(self) -> List[Tuple[int, int, str]]:
        with self._lock:
            return sorted(self._held)

    def _discard_mailbox(self, slots: List[Tuple[int, int, str]]) -> None:
        """Best-effort: tell the conductor to drop undelivered
        descriptors whose chunks are being freed — a descriptor naming
        dead chunks must neither stay deliverable (a late recv would
        hit an opaque fetch timeout instead of the channel's clear
        one) nor leak toward the mailbox cap."""
        try:
            self._worker.conductor.notify(
                "pipeline_channel_discard",
                [self._key(s, mb, k) for (s, mb, k) in slots])
        except Exception:  # noqa: BLE001 — conductor mid-shutdown
            pass

    def close(self) -> None:
        """Drop every held chunk (and its undelivered descriptors)
        and the pubsub callback; in-flight prefetch polls exit on the
        closed flag."""
        self._closed = True
        with self._cv:
            self._cv.notify_all()  # wake prefetch polls so they exit
        try:
            self._worker.unsubscribe_channel("pipeline", self._on_msg)
        except Exception:  # noqa: BLE001 — worker already torn down
            pass
        with self._lock:
            slots = list(self._held)
            self._held.clear()
            self._prefetched.clear()
        if slots:
            self._discard_mailbox(slots)


__all__ = ["ActivationChannel", "ChannelStats"]
