"""Prometheus surface of the MPMD pipeline subsystem — lazily created
so importing ray_tpu.mpmd never spawns a metrics pusher (the weights /
kvcache pattern). Both ride the util.metrics conductor-push pipeline
into /api/metrics and `ray_tpu metrics`:

- ray_tpu_pipeline_bubble_fraction        per-stage idle fraction of the
                                          last pipeline step (bubble_wait
                                          over step wall time)
- ray_tpu_pipeline_activations_bytes_total  microbatch tensor bytes moved
                                          through the activation/gradient
                                          channels, by direction
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

# Rebound ONCE, to a fully-built dict: the unlocked fast path can only
# ever observe None or the complete registry, never a partial one.
_metrics: Optional[Dict[str, Any]] = None
_lock = threading.Lock()


def pipeline_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = dict(
                bubble_fraction=Gauge(
                    "ray_tpu_pipeline_bubble_fraction",
                    "per-stage pipeline bubble: bubble_wait over step "
                    "wall time for the most recent step",
                    tag_keys=("pipeline", "stage")),
                activations_bytes=Counter(
                    "ray_tpu_pipeline_activations_bytes_total",
                    "microbatch activation/gradient bytes through the "
                    "MPMD channels (chunked object-plane transfer)",
                    tag_keys=("pipeline", "stage", "direction")))
    return _metrics
