"""PipelineTrainer: the train-API entrypoint for MPMD pipelines.

The JaxTrainer analog for multi-program execution: where JaxTrainer
runs ONE program (SPMD over a mesh, the ``pp`` axis included),
PipelineTrainer runs ``ScalingConfig.num_stages`` separately-compiled
stage programs on stage-gangs formed by the :class:`PipelineConductor`,
with activations streaming between them over the chunked object plane.

Prefer this over the SPMD ``pp`` mesh axis when the model does not fit
one slice's program, when per-stage compile time matters (stages trace
independently), or when stages are heterogeneous; prefer the SPMD axis
when one jit program fits and XLA's ppermute overlap is enough (see
README "MPMD pipelines").
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import Result

from .conductor import PipelineConductor


class PipelineTrainer:
    """fit() forms the stage-gangs, drives the schedule, returns a
    train-style :class:`Result` whose metrics history is the last
    stage's per-step loss trajectory."""

    def __init__(self, stage_fns: Sequence[Callable],
                 stage_params: Sequence[Any],
                 loss_fn: Callable,
                 optimizer, *,
                 data_fn: Callable[[int], Any],
                 num_microbatches: int,
                 num_steps: int = 1,
                 schedule: str = "1f1b",
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 recv_timeout: float = 60.0):
        self.scaling_config = scaling_config or ScalingConfig(
            num_stages=len(stage_fns))
        if self.scaling_config.num_stages != len(stage_fns):
            raise ValueError(
                f"ScalingConfig.num_stages="
                f"{self.scaling_config.num_stages} but {len(stage_fns)} "
                "stage fns were given")
        if self.scaling_config.num_workers not in (
                1, self.scaling_config.num_stages):
            # one host per stage today; a num_workers that implies
            # multi-host stage-gangs must fail loudly, not silently
            # downgrade to single-process stages
            raise NotImplementedError(
                f"num_workers={self.scaling_config.num_workers} with "
                f"num_stages={self.scaling_config.num_stages}: "
                "stage-gangs run one host per stage today (multi-host "
                "stage-gangs are a ROADMAP follow-up)")
        self.run_config = run_config or RunConfig()
        self.stage_fns = list(stage_fns)
        self.stage_params = list(stage_params)
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.data_fn = data_fn
        self.num_microbatches = int(num_microbatches)
        self.num_steps = int(num_steps)
        self.schedule = schedule
        self.recv_timeout = float(recv_timeout)
        self.conductor: Optional[PipelineConductor] = None

    def fit(self) -> Result:
        import uuid

        # anonymous runs get a unique registry name: a shared constant
        # default would let a second concurrent fit() reopen — and
        # generation-fence-kill — the first run's pipeline
        name = self.run_config.name or f"pipeline/{uuid.uuid4().hex[:8]}"
        pipe = PipelineConductor(
            name, self.stage_fns, self.stage_params, self.optimizer,
            self.loss_fn, num_microbatches=self.num_microbatches,
            schedule=self.schedule,
            resources_per_stage=dict(
                self.scaling_config.resources_per_worker or {}),
        )
        self.conductor = pipe
        history: List[Dict[str, Any]] = []
        try:
            pipe.form()
            out = pipe.run(self.num_steps, self.data_fn,
                           recv_timeout=self.recv_timeout)
        except Exception as e:  # noqa: BLE001 — surface as train Result
            pipe.close()
            self.conductor = None
            return Result(error=e, metrics={}, metrics_history=[])
        except BaseException:
            # Ctrl-C/SystemExit mid-run: still release the stage
            # actors, placement group, and registry entry — deliberate
            # stops must not leak a live gang (JaxTrainer's policy)
            pipe.close()
            self.conductor = None
            raise
        for step, loss in enumerate(out["losses"]):
            history.append({"loss": loss, "step": step,
                            "_time": time.time()})
        metrics: Dict[str, Any] = dict(history[-1]) if history else {}
        metrics["bubble_fraction"] = [
            s.get("bubble_fraction") for s in out["stages"]]
        pipe.close()
        self.conductor = None
        return Result(metrics=metrics, metrics_history=history)


__all__ = ["PipelineTrainer"]
