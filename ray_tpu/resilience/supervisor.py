"""Gang supervision for workers-mode training.

A gang is only as alive as its slowest-dying member: when one rank's
process dies mid-collective, the survivors block inside XLA until some
distant timeout. The supervisor rides the conductor's actor-death pubsub
(the same channel actor handles use for restart tracking) so peer death
is detected in milliseconds, cancels the survivors (their collectives
can never complete), and leaves the restart decision to the trainer's
retry loop — which applies exponential backoff and, when capacity
shrank (the dead host is quarantined or gone), an elastic re-form onto
a smaller ``dcn_dp`` axis via :func:`elastic_reform`.
"""
from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


def backoff_delay(attempt: int, base_s: Optional[float] = None,
                  cap_s: Optional[float] = None,
                  jitter_frac: float = 0.25,
                  rand=random.random) -> float:
    """Exponential backoff with jitter for restart attempt `attempt`
    (1-based): min(cap, base * 2**(attempt-1)) * (1 + jitter*U[0,1)).
    Defaults come from the flag table (RAY_TPU_RESTART_BACKOFF_*)."""
    from ray_tpu._private.config import config

    if base_s is None:
        base_s = config.restart_backoff_base_s
    if cap_s is None:
        cap_s = config.restart_backoff_max_s
    attempt = max(1, int(attempt))
    delay = min(float(cap_s), float(base_s) * (2.0 ** (attempt - 1)))
    return delay * (1.0 + max(0.0, jitter_frac) * rand())


def elastic_reform(scaling, sharding, available_workers: int
                   ) -> Optional[Tuple[Any, Any]]:
    """Shrink a gang to fit reduced capacity, or None when no valid
    smaller shape exists.

    Only active when ``ScalingConfig.min_workers`` is set (the user's
    opt-in to elastic semantics). Multi-slice gangs shrink by whole
    slices — the workers-per-slice count is the ICI mesh shape and must
    not change — and a ``ShardingConfig`` whose ``dcn_dp`` equals the
    slice count follows it down, so the re-formed hybrid mesh is the
    same ICI layout over fewer DCN groups (dcn_dp=1 lowers to a flat
    single-slice mesh). Flat gangs shrink to exactly the available
    worker count. Returns (new_scaling, new_sharding)."""
    floor = getattr(scaling, "min_workers", None)
    n = scaling.num_workers
    if floor is None or available_workers >= n or n <= 1:
        return None
    slices = max(1, getattr(scaling, "num_slices", 1))
    if slices > 1:
        per_slice = n // slices
        new_slices = available_workers // per_slice
        new_n = new_slices * per_slice
    else:
        new_n = available_workers
        new_slices = 1
    if new_n < max(1, int(floor)) or new_n <= 0:
        return None
    new_scaling = dataclasses.replace(scaling, num_workers=new_n,
                                      num_slices=new_slices)
    new_sharding = sharding
    if sharding is not None and slices > 1 and \
            getattr(sharding, "dcn_dp", 1) == slices:
        new_sharding = dataclasses.replace(sharding, dcn_dp=new_slices)
    return new_scaling, new_sharding


class GangSupervisor:
    """Context manager watching one gang's actors for peer death.

    On the first DEAD member: records the failure (cause + host) to the
    conductor's resilience log and kills every surviving member so the
    driver's blocking ``get`` fails fast instead of waiting out a wedged
    collective. The kills go through ``kill_actor`` and are therefore
    *expected* deaths — only the original casualty charges the failure
    domain tracker.
    """

    def __init__(self, handles: List[Any], run_id: str = ""):
        self.run_id = run_id
        self._handles: Dict[str, Any] = {h.actor_id: h for h in handles}
        self._worker = None
        self._lock = threading.Lock()
        self.first_death: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------ lifecycle

    def __enter__(self) -> "GangSupervisor":
        from ray_tpu._private import worker as worker_mod

        self._worker = worker_mod.global_worker
        if self._worker is not None:
            self._worker.subscribe_channel("actor_state", self._on_state)
        return self

    def __exit__(self, *exc) -> None:
        if self._worker is not None:
            self._worker.unsubscribe_channel("actor_state", self._on_state)
        return None

    # ------------------------------------------------------------- handling

    def _on_state(self, msg: Any) -> None:
        if not isinstance(msg, dict) or msg.get("state") != "DEAD":
            return
        actor_id = msg.get("actor_id")
        if actor_id not in self._handles:
            return
        with self._lock:
            if self.first_death is not None:
                return  # survivors we kill below also publish DEAD
            self.first_death = {"actor_id": actor_id, "ts": time.time()}
        # Finish OFF the pubsub dispatch thread: cause lookup and the
        # survivor kills are conductor RPCs of their own.
        threading.Thread(target=self._handle_death, args=(actor_id,),
                         name="gang-supervisor", daemon=True).start()

    def _handle_death(self, actor_id: str) -> None:
        w = self._worker
        if w is None:
            return
        cause = ""
        try:
            info = w.conductor.call("get_actor_info", actor_id,
                                    timeout=5.0)
            cause = info.get("death_cause") or ""
        except Exception:  # noqa: BLE001 — conductor mid-restart
            pass
        with self._lock:
            if self.first_death is not None:
                self.first_death["cause"] = cause
        try:
            w.conductor.call("report_resilience_event", {
                "kind": "gang_peer_death", "run_id": self.run_id,
                "actor_id": actor_id, "detail": cause}, timeout=5.0)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        for aid in self._handles:
            if aid == actor_id:
                continue
            try:
                w.conductor.call("kill_actor", aid, True, timeout=10.0)
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
