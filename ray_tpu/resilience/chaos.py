"""Chaos harness: deterministic, scriptable fault injection.

The recovery machinery in this package is only trustworthy if exact
failure scenarios can be replayed in tests — "kill rank 2 at step 5",
"preempt host H with 3 seconds of grace mid-run", "delay heartbeats by
500ms". A chaos *plan* is a JSON list of such actions, carried in the
``RAY_TPU_CHAOS_PLAN`` env var (inline JSON, or ``@/path/plan.json``)
or handed to the trainer programmatically; a :class:`ChaosMonkey` built
from the plan is consulted at every training step boundary
(``ray_tpu.train.report``) and fires each matching action exactly once.

Actions (all fields beyond ``action`` optional unless noted):

- ``{"action": "raise", "rank": R, "at_step": S}`` — raise
  :class:`ChaosError` inside the training loop (survivable failure; the
  trainer's retry path catches it).
- ``{"action": "kill", "rank": R, "at_step": S}`` — hard ``os._exit``
  of the rank's process (worker death; exercises death-pub detection).
- ``{"action": "preempt", "node": N, "grace_s": G, "at_step": S}`` —
  report a preemption for node N (a node id, ``"head"``, or ``"self"``
  = the firing rank's host) to the conductor, which broadcasts the
  checkpoint-now signal and starts draining the host.
- ``{"action": "delay_heartbeats", "ms": M}`` — node agents stretch
  their heartbeat period by M ms (consulted each beat, not stepwise).
- ``{"action": "bounce_conductor", "at_step": S}`` — matched by
  :meth:`ChaosPlan.external_actions`; executed by the test harness
  (only it owns the conductor's lifecycle), not by the monkey.

``at_step`` compares against the step number being reported (the
``step`` metric when present, else the session's report count, both
1-based for the first report). ``attempt`` (default 0) scopes an action
to one restart generation so a resumed run replaying the same step
numbers does not re-fire it; ``"attempt": "any"`` fires every time the
step matches. ``rank`` defaults to 0 for cluster-wide actions
(``preempt``) and is required for ``raise``/``kill``.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

ENV_VAR = "RAY_TPU_CHAOS_PLAN"

_IN_PROCESS = ("raise", "kill", "preempt")
_EXTERNAL = ("bounce_conductor",)
_PASSIVE = ("delay_heartbeats",)


class ChaosError(RuntimeError):
    """A scripted, survivable failure injected by the chaos harness."""


@dataclass
class ChaosAction:
    action: str
    at_step: int = 0
    rank: Optional[int] = None
    attempt: Any = 0            # int generation, or "any"
    node: Optional[str] = None  # preempt: node id | "head" | "self"
    grace_s: Optional[float] = None
    ms: float = 0.0             # delay_heartbeats

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosAction":
        action = str(d.get("action", ""))
        known = _IN_PROCESS + _EXTERNAL + _PASSIVE
        if action not in known:
            raise ValueError(f"unknown chaos action {action!r}; "
                             f"known: {sorted(known)}")
        if action in ("raise", "kill") and d.get("rank") is None:
            raise ValueError(f"chaos action {action!r} requires a rank")
        return cls(action=action,
                   at_step=int(d.get("at_step", 0)),
                   rank=(None if d.get("rank") is None
                         else int(d["rank"])),
                   attempt=d.get("attempt", 0),
                   node=d.get("node"),
                   grace_s=(None if d.get("grace_s") is None
                            else float(d["grace_s"])),
                   ms=float(d.get("ms", 0.0)))

    def matches(self, step: int, rank: int, attempt: int) -> bool:
        if self.action in _PASSIVE:
            return False  # consulted out-of-band, not stepwise
        if self.attempt != "any" and int(self.attempt) != attempt:
            return False
        if self.at_step != step:
            return False
        want = 0 if self.rank is None else self.rank
        return want == rank


class ChaosPlan:
    """An ordered list of actions, parsed from JSON."""

    def __init__(self, actions: List[ChaosAction], spec: str = ""):
        self.actions = list(actions)
        self.spec = spec

    def __bool__(self) -> bool:
        return bool(self.actions)

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> "ChaosPlan":
        """Parse inline JSON or ``@/path/to/plan.json``; None/"" is the
        empty plan. A malformed plan raises — silently dropping scripted
        faults would make a chaos test vacuously green."""
        if not spec:
            return cls([], "")
        text = spec
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                text = f.read()
        data = json.loads(text)
        if isinstance(data, dict):
            data = data.get("actions", [])
        return cls([ChaosAction.from_dict(d) for d in data], spec)

    @classmethod
    def from_env(cls) -> "ChaosPlan":
        return cls.from_spec(os.environ.get(ENV_VAR))

    def heartbeat_delay_s(self) -> float:
        """Extra node-agent heartbeat delay scripted by the plan."""
        return sum(a.ms for a in self.actions
                   if a.action == "delay_heartbeats") / 1000.0

    def external_actions(self, step: int, attempt: int = 0
                         ) -> List[ChaosAction]:
        """Actions the harness itself must execute at this step (e.g.
        bounce_conductor) — the monkey cannot, it lives inside the run."""
        return [a for a in self.actions
                if a.action in _EXTERNAL
                and a.matches(step, a.rank or 0, attempt)]


_HB_DELAY_CACHE: Optional[tuple] = None  # (env spec, parsed delay)


def heartbeat_delay_s() -> float:
    """Env-plan heartbeat stretch, for the node agent's beat loop.
    Cached per env value (the agent consults this every beat — no
    point re-parsing an @file plan each second); parse failures count
    as no delay here — the agent must keep heartbeating no matter what
    is in the env."""
    global _HB_DELAY_CACHE
    spec = os.environ.get(ENV_VAR)
    if _HB_DELAY_CACHE is not None and _HB_DELAY_CACHE[0] == spec:
        return _HB_DELAY_CACHE[1]
    try:
        delay = ChaosPlan.from_spec(spec).heartbeat_delay_s()
    except Exception:  # noqa: BLE001
        delay = 0.0
    _HB_DELAY_CACHE = (spec, delay)
    return delay


class ChaosMonkey:
    """Per-process executor of a plan's in-process actions.

    Created by the trainer for each fit attempt and consulted from
    ``ray_tpu.train.report`` at every step boundary. Each action fires
    at most once per monkey; the ``attempt`` field on actions provides
    cross-restart determinism (a restarted run is a new monkey with a
    new attempt number).
    """

    def __init__(self, plan: ChaosPlan, rank: int = 0, attempt: int = 0,
                 conductor_call: Optional[Callable[..., Any]] = None):
        self.plan = plan
        self.rank = int(rank)
        self.attempt = int(attempt)
        self._conductor_call = conductor_call
        self._fired: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- firing

    def on_step(self, step: int) -> None:
        """Fire every in-process action matching (step, rank, attempt).
        May raise ChaosError or terminate the process — by design."""
        for idx, a in enumerate(self.plan.actions):
            if a.action not in _IN_PROCESS:
                continue
            with self._lock:
                if idx in self._fired:
                    continue
                if not a.matches(step, self.rank, self.attempt):
                    continue
                self._fired.add(idx)
            self._execute(a, step)

    def _execute(self, a: ChaosAction, step: int) -> None:
        self._report_event(a, step)
        if a.action == "raise":
            raise ChaosError(
                f"chaos: injected failure at rank {self.rank} "
                f"step {step} (attempt {self.attempt})")
        if a.action == "kill":
            os._exit(137)
        if a.action == "preempt":
            self._preempt(a)

    def _call(self, method: str, *args, **kwargs) -> Any:
        if self._conductor_call is not None:
            return self._conductor_call(method, *args, **kwargs)
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            return None
        return w.conductor.call(method, *args, timeout=10.0, **kwargs)

    def _preempt(self, a: ChaosAction) -> None:
        node_id, worker_id = a.node, None
        if a.node in (None, "self"):
            node_id = None
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            worker_id = w.worker_id if w is not None else None
        elif a.node == "head":
            node_id = None  # conductor defaults to its head node
        try:
            self._call("report_preemption", node_id, worker_id,
                       a.grace_s, "chaos")
        except Exception:  # noqa: BLE001 — conductor mid-bounce: the
            pass           # preempt injection is lost, the plan is not

    def _report_event(self, a: ChaosAction, step: int) -> None:
        try:
            self._call("report_resilience_event", {
                "kind": "chaos", "action": a.action, "rank": self.rank,
                "step": step, "attempt": self.attempt, "node": a.node})
        except Exception:  # noqa: BLE001 — telemetry only
            pass


def monkey_from_spec(spec: Optional[str], rank: int = 0,
                     attempt: int = 0) -> Optional[ChaosMonkey]:
    """Build a monkey when `spec` (or, if None, the env) carries a
    plan; None when there is no chaos configured."""
    plan = (ChaosPlan.from_env() if spec is None
            else ChaosPlan.from_spec(spec))
    if not plan:
        return None
    return ChaosMonkey(plan, rank=rank, attempt=attempt)
