"""Chaos harness: deterministic, scriptable fault injection.

The recovery machinery in this package is only trustworthy if exact
failure scenarios can be replayed in tests — "kill rank 2 at step 5",
"preempt host H with 3 seconds of grace mid-run", "delay heartbeats by
500ms". A chaos *plan* is a JSON list of such actions, carried in the
``RAY_TPU_CHAOS_PLAN`` env var (inline JSON, or ``@/path/plan.json``)
or handed to the trainer programmatically; a :class:`ChaosMonkey` built
from the plan is consulted at every training step boundary
(``ray_tpu.train.report``) and fires each matching action exactly once.

Actions (all fields beyond ``action`` optional unless noted):

- ``{"action": "raise", "rank": R, "at_step": S}`` — raise
  :class:`ChaosError` inside the training loop (survivable failure; the
  trainer's retry path catches it).
- ``{"action": "kill", "rank": R, "at_step": S}`` — hard ``os._exit``
  of the rank's process (worker death; exercises death-pub detection).
- ``{"action": "preempt", "node": N, "grace_s": G, "at_step": S}`` —
  report a preemption for node N (a node id, ``"head"``, or ``"self"``
  = the firing rank's host) to the conductor, which broadcasts the
  checkpoint-now signal and starts draining the host.
- ``{"action": "delay_heartbeats", "ms": M}`` — node agents stretch
  their heartbeat period by M ms (consulted each beat, not stepwise).
- ``{"action": "bounce_conductor", "at_step": S}`` — matched by
  :meth:`ChaosPlan.external_actions`; executed by the test harness
  (only it owns the conductor's lifecycle), not by the monkey.

Serving-plane actions (consulted by the disagg tier replicas through a
:class:`ServeChaosMonkey`, exactly-once per replica process like the
training ops; see serve/disagg.py):

- ``{"action": "kill_replica", "role": "prefill"|"decode",
  "at": "token:K"|"request:N", "replica": R}`` — hard ``os._exit`` of
  the matching tier replica's process. ``at=token:K`` fires when the
  replica has served its K-th decoded token (mid-stream death — the
  request-failover path); ``at=request:N`` fires at the start of its
  N-th request (prefill death before the KV transfer is acked).
  ``replica`` (default 0) is the replica's creation index within its
  role, so one plan kills exactly one replica and the self-healer's
  replacement (a higher index) does not re-fire.
- ``{"action": "drop_connection", "at": "token:K"|"request:N",
  "replica": R}`` — the HTTP gateway (serve/gateway.py) hard-aborts
  the CLIENT socket of the request that crosses the K-th served token
  (or at admission of the N-th request): a deterministic mid-stream
  client disconnect, proving the disconnect-reap path (decode
  cancelled, ``shed cause=disconnect``). ``role`` defaults to
  ``gateway``; the gateway replica dies with nothing — only the
  connection does (its monkey gets a flag-latching exit_fn).
- ``{"action": "delay_chunk_fetch", "ms": M}`` — every ChunkFetcher
  pull sleeps M ms first (consulted out-of-band per fetch, like
  delay_heartbeats), stretching KV-transfer and weight-fetch latency.
- ``{"action": "evict_storm", "role": "prefill", "blocks": B,
  "at": "request:N", "replica": R}`` — force-evict B blocks from the
  matching prefill replica's HBM prefix pool at the start of its N-th
  request (deterministic cache-pressure injection: with the KV plane
  attached the storm spills into the tier-2 host arena instead of
  destroying the prefixes — serve/kvplane.py's chaos test asserts
  zero wrong outputs). Non-lethal: the replica consults
  ``take_storm()`` and applies the eviction itself.

``at_step`` compares against the step number being reported (the
``step`` metric when present, else the session's report count, both
1-based for the first report). ``attempt`` (default 0) scopes an action
to one restart generation so a resumed run replaying the same step
numbers does not re-fire it; ``"attempt": "any"`` fires every time the
step matches. ``rank`` defaults to 0 for cluster-wide actions
(``preempt``) and is required for ``raise``/``kill``.
"""
from __future__ import annotations

import json
import os
import re
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

ENV_VAR = "RAY_TPU_CHAOS_PLAN"

_IN_PROCESS = ("raise", "kill", "preempt")
_EXTERNAL = ("bounce_conductor",)
_PASSIVE = ("delay_heartbeats", "delay_chunk_fetch")
_SERVE = ("kill_replica", "drop_connection", "evict_storm")

_AT_RE = re.compile(r"^(token|request):(\d+)$")


class ChaosError(RuntimeError):
    """A scripted, survivable failure injected by the chaos harness."""


@dataclass
class ChaosAction:
    action: str
    at_step: int = 0
    rank: Optional[int] = None
    attempt: Any = 0            # int generation, or "any"
    node: Optional[str] = None  # preempt: node id | "head" | "self"
    grace_s: Optional[float] = None
    ms: float = 0.0             # delay_heartbeats / delay_chunk_fetch
    role: Optional[str] = None  # kill_replica: prefill | decode
    at: Optional[str] = None    # kill_replica: "token:K" | "request:N"
    replica: int = 0            # kill_replica: creation index in role
    blocks: int = 0             # evict_storm: HBM blocks to force-evict

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChaosAction":
        action = str(d.get("action", ""))
        known = _IN_PROCESS + _EXTERNAL + _PASSIVE + _SERVE
        if action not in known:
            raise ValueError(f"unknown chaos action {action!r}; "
                             f"known: {sorted(known)}")
        if action in ("raise", "kill") and d.get("rank") is None:
            raise ValueError(f"chaos action {action!r} requires a rank")
        if action == "kill_replica":
            if d.get("role") not in ("prefill", "decode"):
                raise ValueError(
                    "chaos action 'kill_replica' requires "
                    "role=prefill|decode")
            if not _AT_RE.match(str(d.get("at", ""))):
                raise ValueError(
                    "chaos action 'kill_replica' requires "
                    "at='token:K'|'request:N'")
        if action == "evict_storm":
            if d.get("role") not in (None, "prefill"):
                raise ValueError(
                    "chaos action 'evict_storm' fires at a prefill "
                    "replica's prefix pool (role=prefill or omitted)")
            d = dict(d, role="prefill")
            if int(d.get("blocks", 0)) < 1:
                raise ValueError(
                    "chaos action 'evict_storm' requires blocks>=1")
            if not _AT_RE.match(str(d.get("at", ""))):
                raise ValueError(
                    "chaos action 'evict_storm' requires "
                    "at='token:K'|'request:N'")
        if action == "drop_connection":
            if d.get("role") not in (None, "gateway"):
                raise ValueError(
                    "chaos action 'drop_connection' fires at the "
                    "gateway (role=gateway or omitted)")
            d = dict(d, role="gateway")
            if not _AT_RE.match(str(d.get("at", ""))):
                raise ValueError(
                    "chaos action 'drop_connection' requires "
                    "at='token:K'|'request:N'")
        return cls(action=action,
                   at_step=int(d.get("at_step", 0)),
                   rank=(None if d.get("rank") is None
                         else int(d["rank"])),
                   attempt=d.get("attempt", 0),
                   node=d.get("node"),
                   grace_s=(None if d.get("grace_s") is None
                            else float(d["grace_s"])),
                   ms=float(d.get("ms", 0.0)),
                   role=d.get("role"),
                   at=(None if d.get("at") is None else str(d["at"])),
                   replica=int(d.get("replica", 0)),
                   blocks=int(d.get("blocks", 0)))

    def at_spec(self) -> Optional[tuple]:
        """("token"|"request", N) for a kill_replica action."""
        if not self.at:
            return None
        m = _AT_RE.match(self.at)
        return (m.group(1), int(m.group(2))) if m else None

    def matches(self, step: int, rank: int, attempt: int) -> bool:
        if self.action in _PASSIVE or self.action in _SERVE:
            return False  # consulted out-of-band, not stepwise
        if self.attempt != "any" and int(self.attempt) != attempt:
            return False
        if self.at_step != step:
            return False
        want = 0 if self.rank is None else self.rank
        return want == rank


class ChaosPlan:
    """An ordered list of actions, parsed from JSON."""

    def __init__(self, actions: List[ChaosAction], spec: str = ""):
        self.actions = list(actions)
        self.spec = spec

    def __bool__(self) -> bool:
        return bool(self.actions)

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> "ChaosPlan":
        """Parse inline JSON or ``@/path/to/plan.json``; None/"" is the
        empty plan. A malformed plan raises — silently dropping scripted
        faults would make a chaos test vacuously green."""
        if not spec:
            return cls([], "")
        text = spec
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                text = f.read()
        data = json.loads(text)
        if isinstance(data, dict):
            data = data.get("actions", [])
        return cls([ChaosAction.from_dict(d) for d in data], spec)

    @classmethod
    def from_env(cls) -> "ChaosPlan":
        return cls.from_spec(os.environ.get(ENV_VAR))

    def heartbeat_delay_s(self) -> float:
        """Extra node-agent heartbeat delay scripted by the plan."""
        return sum(a.ms for a in self.actions
                   if a.action == "delay_heartbeats") / 1000.0

    def chunk_fetch_delay_s(self) -> float:
        """Extra per-pull ChunkFetcher delay scripted by the plan."""
        return sum(a.ms for a in self.actions
                   if a.action == "delay_chunk_fetch") / 1000.0

    def serve_actions(self, role: str, replica: int
                      ) -> List[ChaosAction]:
        """The serving-plane actions (kill_replica / drop_connection)
        scoped to one tier or gateway replica."""
        return [a for a in self.actions
                if a.action in _SERVE and a.role == role
                and a.replica == int(replica)]

    def external_actions(self, step: int, attempt: int = 0
                         ) -> List[ChaosAction]:
        """Actions the harness itself must execute at this step (e.g.
        bounce_conductor) — the monkey cannot, it lives inside the run."""
        return [a for a in self.actions
                if a.action in _EXTERNAL
                and a.matches(step, a.rank or 0, attempt)]


_HB_DELAY_CACHE: Optional[tuple] = None  # (env spec, parsed delay)
_CF_DELAY_CACHE: Optional[tuple] = None  # (env spec, parsed delay)


def heartbeat_delay_s() -> float:
    """Env-plan heartbeat stretch, for the node agent's beat loop.
    Cached per env value (the agent consults this every beat — no
    point re-parsing an @file plan each second); parse failures count
    as no delay here — the agent must keep heartbeating no matter what
    is in the env."""
    global _HB_DELAY_CACHE
    spec = os.environ.get(ENV_VAR)
    if _HB_DELAY_CACHE is not None and _HB_DELAY_CACHE[0] == spec:
        return _HB_DELAY_CACHE[1]
    try:
        delay = ChaosPlan.from_spec(spec).heartbeat_delay_s()
    except Exception:  # noqa: BLE001
        delay = 0.0
    _HB_DELAY_CACHE = (spec, delay)
    return delay


def chunk_fetch_delay_s() -> float:
    """Env-plan chunk-fetch stretch, for util.chunks.ChunkFetcher
    (consulted once per pull — same cache discipline as the heartbeat
    delay; parse failures count as no delay, a fetch must proceed no
    matter what is in the env)."""
    global _CF_DELAY_CACHE
    spec = os.environ.get(ENV_VAR)
    if _CF_DELAY_CACHE is not None and _CF_DELAY_CACHE[0] == spec:
        return _CF_DELAY_CACHE[1]
    try:
        delay = ChaosPlan.from_spec(spec).chunk_fetch_delay_s()
    except Exception:  # noqa: BLE001
        delay = 0.0
    _CF_DELAY_CACHE = (spec, delay)
    return delay


class ChaosMonkey:
    """Per-process executor of a plan's in-process actions.

    Created by the trainer for each fit attempt and consulted from
    ``ray_tpu.train.report`` at every step boundary. Each action fires
    at most once per monkey; the ``attempt`` field on actions provides
    cross-restart determinism (a restarted run is a new monkey with a
    new attempt number).
    """

    def __init__(self, plan: ChaosPlan, rank: int = 0, attempt: int = 0,
                 conductor_call: Optional[Callable[..., Any]] = None):
        self.plan = plan
        self.rank = int(rank)
        self.attempt = int(attempt)
        self._conductor_call = conductor_call
        self._fired: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- firing

    def on_step(self, step: int) -> None:
        """Fire every in-process action matching (step, rank, attempt).
        May raise ChaosError or terminate the process — by design."""
        for idx, a in enumerate(self.plan.actions):
            if a.action not in _IN_PROCESS:
                continue
            with self._lock:
                if idx in self._fired:
                    continue
                if not a.matches(step, self.rank, self.attempt):
                    continue
                self._fired.add(idx)
            self._execute(a, step)

    def _execute(self, a: ChaosAction, step: int) -> None:
        self._report_event(a, step)
        if a.action == "raise":
            raise ChaosError(
                f"chaos: injected failure at rank {self.rank} "
                f"step {step} (attempt {self.attempt})")
        if a.action == "kill":
            os._exit(137)
        if a.action == "preempt":
            self._preempt(a)

    def _call(self, method: str, *args, **kwargs) -> Any:
        if self._conductor_call is not None:
            return self._conductor_call(method, *args, **kwargs)
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            return None
        return w.conductor.call(method, *args, timeout=10.0, **kwargs)

    def _preempt(self, a: ChaosAction) -> None:
        node_id, worker_id = a.node, None
        if a.node in (None, "self"):
            node_id = None
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            worker_id = w.worker_id if w is not None else None
        elif a.node == "head":
            node_id = None  # conductor defaults to its head node
        try:
            self._call("report_preemption", node_id, worker_id,
                       a.grace_s, "chaos")
        except Exception:  # noqa: BLE001 — conductor mid-bounce: the
            pass           # preempt injection is lost, the plan is not

    def _report_event(self, a: ChaosAction, step: int) -> None:
        try:
            self._call("report_resilience_event", {
                "kind": "chaos", "action": a.action, "rank": self.rank,
                "step": step, "attempt": self.attempt, "node": a.node})
        except Exception:  # noqa: BLE001 — telemetry only
            pass


def monkey_from_spec(spec: Optional[str], rank: int = 0,
                     attempt: int = 0) -> Optional[ChaosMonkey]:
    """Build a monkey when `spec` (or, if None, the env) carries a
    plan; None when there is no chaos configured."""
    plan = (ChaosPlan.from_env() if spec is None
            else ChaosPlan.from_spec(spec))
    if not plan:
        return None
    return ChaosMonkey(plan, rank=rank, attempt=attempt)


class ServeChaosMonkey:
    """Per-replica-process executor of a plan's kill_replica actions.

    Created by a disagg tier replica (serve/disagg.py PrefillServer /
    DecodeServer) with its role and creation index; consulted at every
    request admission (``on_request``) and every served token
    (``on_tokens``). Each action fires at most once — the process dies
    with it, but the latch also guards the in-process test doubles.
    ``at`` counts are cumulative per replica (the K-th token / N-th
    request THIS replica serves), which is what makes a mid-stream
    decode death deterministic under concurrent traffic."""

    def __init__(self, plan: ChaosPlan, role: str, replica: int = 0,
                 exit_fn: Callable[[int], Any] = os._exit):
        self.role = str(role)
        self.replica = int(replica)
        self.actions = plan.serve_actions(self.role, self.replica)
        self._exit = exit_fn
        self._lock = threading.Lock()
        self._fired: set = set()
        self._tokens = 0
        self._requests = 0
        # evict_storm is non-lethal: firing latches the block count
        # here and the replica applies the eviction itself via
        # take_storm() (the monkey has no handle on the prefix pool)
        self._pending_storm = 0

    def __bool__(self) -> bool:
        return bool(self.actions)

    def take_storm(self) -> int:
        """Pop the pending evict_storm block count (0 when none is
        due). The prefill replica consults this right after
        ``on_request`` and force-evicts that many HBM blocks."""
        with self._lock:
            n, self._pending_storm = self._pending_storm, 0
        return n

    def reset_counts(self) -> None:
        """Zero the cumulative request/token counters. bench_serve
        calls this on every replica at measurement start, so a plan's
        ``at=request:N`` / ``at=token:K`` counts the Nth MEASURED
        request / Kth measured token instead of including warm-up
        traffic (the PR-12 known limit). LETHAL latches persist — a
        fired kill already took its process, the latch only guards
        in-process test doubles — but non-lethal evict_storm latches
        re-arm (and any warm-up-fired pending count is dropped): a
        storm that tripped during warm-up must still fire at the Nth
        measured request, or the measured run storms nothing."""
        with self._lock:
            self._tokens = 0
            self._requests = 0
            self._fired -= {i for i, a in enumerate(self.actions)
                            if a.action == "evict_storm"}
            self._pending_storm = 0

    # ------------------------------------------------------------- firing

    def on_request(self) -> None:
        """One request admitted (prefill call / decode adoption)."""
        with self._lock:
            self._requests += 1
            fire = self._due_locked("request", self._requests)
        if fire is not None:
            self._fire(fire)

    def on_tokens(self, n: int = 1) -> None:
        """`n` more tokens served by this replica."""
        with self._lock:
            self._tokens += int(n)
            fire = self._due_locked("token", self._tokens)
        if fire is not None:
            self._fire(fire)

    def _due_locked(self, kind: str, count: int) -> Optional[ChaosAction]:
        for idx, a in enumerate(self.actions):
            if idx in self._fired:
                continue
            spec = a.at_spec()
            if spec is not None and spec[0] == kind and count >= spec[1]:
                self._fired.add(idx)
                return a
        return None

    def _fire(self, a: ChaosAction) -> None:
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            if w is not None:
                ev = {"kind": "chaos", "action": a.action,
                      "role": self.role, "replica": self.replica,
                      "at": a.at, "tokens": self._tokens,
                      "requests": self._requests}
                if a.action == "evict_storm":
                    ev["blocks"] = a.blocks
                w.conductor.notify("report_resilience_event", ev)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        if a.action == "evict_storm":
            # non-lethal: the replica pops the count via take_storm()
            with self._lock:
                self._pending_storm += max(0, int(a.blocks))
            return
        self._exit(137)


def serve_monkey_from_spec(spec: Optional[str], role: str,
                           replica: int = 0,
                           exit_fn: Callable[[int], Any] = os._exit
                           ) -> Optional[ServeChaosMonkey]:
    """Build a serving monkey when `spec` (or, if None, the env)
    carries serving actions for this (role, replica); None when no
    serving chaos is configured — the hot path then pays a single
    None check per token batch. `exit_fn` is what firing does: tier
    replicas keep the default hard exit; the gateway passes a
    flag-latching fn so a drop_connection kills one SOCKET, not the
    ingress process."""
    try:
        plan = (ChaosPlan.from_env() if spec is None
                else ChaosPlan.from_spec(spec))
    except Exception:
        if spec is not None:
            raise  # an explicit plan must not be silently dropped
        return None  # malformed env plan: serving keeps running
    if not plan:
        return None
    monkey = ServeChaosMonkey(plan, role, replica, exit_fn)
    return monkey if monkey else None
