"""Failure-domain tracking and quarantine.

TPU fleets fail along hardware boundaries — a host with a flaky NIC or a
marginal chip kills every gang scheduled onto it, and the reference's
answer (restart the actor wherever the scheduler likes) lets one bad
host kill the same job five times in a row. The tracker keeps a decayed
failure score per domain (host/slice); domains over the threshold are
*quarantined* — excluded from lease grants, placement-group bundle
assignment, and gang re-formation until the score decays back under the
line (or an operator clears it).

Preemptions are tracked separately as *draining*: a host that announced
a maintenance event is excluded immediately for the grace window — it is
about to disappear, scheduling onto it only manufactures failures — but
draining is not a black mark; if the host survives the window it serves
leases again with a clean score.

Pure in-memory policy, no conductor imports: the conductor owns one
instance under its lock, tests drive it with a fake clock.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class _DomainState:
    score: float = 0.0
    updated: float = 0.0
    failures: int = 0
    last_kind: str = ""
    last_detail: str = ""
    last_failure_ts: float = 0.0      # wall clock, for display
    drain_deadline: Optional[float] = None  # monotonic; None = not draining
    drain_reason: str = ""
    manual: bool = False              # operator quarantine, no decay out
    tripped: bool = False             # score crossed the threshold
    trips: int = 0                    # not-tripped -> tripped transitions
    recent: List[Dict[str, Any]] = field(default_factory=list)


class FailureDomainTracker:
    """Decayed per-domain failure scores with a quarantine threshold.

    `record` adds `weight` to the domain's score; scores halve every
    `half_life_s`, so an ancient incident cannot quarantine a healthy
    host while a burst of failures crosses the threshold fast.

    Quarantine has hysteresis: crossing the threshold trips the latch,
    and the domain stays quarantined until the score decays below HALF
    the threshold (one half-life after the last trip) — without it, a
    score of exactly-threshold would un-quarantine within a millisecond
    of decay, turning the quarantine into a coin flip.
    """

    _RECENT_KEPT = 8

    def __init__(self, threshold: float = 3.0, half_life_s: float = 600.0,
                 exempt: tuple = (),
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = float(threshold)
        self.half_life_s = max(1e-9, float(half_life_s))
        self.exempt = frozenset(exempt)
        self._clock = clock
        self._lock = threading.Lock()
        self._domains: Dict[str, _DomainState] = {}

    # ----------------------------------------------------------- mutation

    def record(self, domain: str, kind: str, weight: float = 1.0,
               detail: str = "") -> float:
        """Charge a failure against `domain`; returns the new score."""
        now = self._clock()
        with self._lock:
            st = self._domains.setdefault(domain, _DomainState(updated=now))
            st.score = self._decayed(st, now) + float(weight)
            st.updated = now
            if st.score >= self.threshold - 1e-9:
                if not st.tripped:
                    st.trips += 1  # the breaker/quarantine OPEN edge
                st.tripped = True
            st.failures += 1
            st.last_kind = kind
            st.last_detail = detail
            st.last_failure_ts = time.time()
            st.recent.append({"ts": st.last_failure_ts, "kind": kind,
                              "weight": float(weight), "detail": detail})
            del st.recent[:-self._RECENT_KEPT]
            return st.score

    def begin_drain(self, domain: str, deadline: float,
                    reason: str = "preemption") -> None:
        """Exclude `domain` until monotonic `deadline` (preemption grace
        window). Extends but never shortens an existing drain."""
        with self._lock:
            st = self._domains.setdefault(
                domain, _DomainState(updated=self._clock()))
            if st.drain_deadline is None or deadline > st.drain_deadline:
                st.drain_deadline = deadline
                st.drain_reason = reason

    def quarantine(self, domain: str, reason: str = "manual") -> None:
        """Operator pin: quarantined regardless of score until cleared."""
        now = self._clock()
        with self._lock:
            st = self._domains.setdefault(domain, _DomainState(updated=now))
            st.manual = True
            st.last_kind = reason

    def clear(self, domain: str) -> bool:
        """Forgive a domain: drop score, drain, and manual pin."""
        with self._lock:
            return self._domains.pop(domain, None) is not None

    # ------------------------------------------------------------ queries

    def _decayed(self, st: _DomainState, now: float) -> float:
        return st.score * 0.5 ** ((now - st.updated) / self.half_life_s)

    def score(self, domain: str) -> float:
        now = self._clock()
        with self._lock:
            st = self._domains.get(domain)
            return self._decayed(st, now) if st is not None else 0.0

    def is_quarantined(self, domain: str) -> bool:
        now = self._clock()
        with self._lock:
            st = self._domains.get(domain)
            if st is None:
                return False
            if st.manual:
                # an operator pin beats the exemption: exempt only
                # guards against AUTO-quarantine (score trips)
                return True
            if domain in self.exempt:
                return False
            if st.tripped and self._decayed(st, now) < self.threshold / 2:
                st.tripped = False  # hysteresis exit: latch released
            return st.tripped

    def trip_count(self, domain: Optional[str] = None) -> int:
        """Quarantine-latch OPEN transitions for one domain (or summed
        over all) — the serving self-healer reports this as its
        circuit-breaker trip counter."""
        with self._lock:
            if domain is not None:
                st = self._domains.get(domain)
                return st.trips if st is not None else 0
            return sum(st.trips for st in self._domains.values())

    def is_draining(self, domain: str) -> bool:
        now = self._clock()
        with self._lock:
            st = self._domains.get(domain)
            return (st is not None and st.drain_deadline is not None
                    and now < st.drain_deadline)

    def is_excluded(self, domain: str) -> bool:
        """Quarantined OR draining — the scheduler's single question."""
        return self.is_quarantined(domain) or self.is_draining(domain)

    def excluded(self) -> List[str]:
        with self._lock:
            names = list(self._domains)
        return [d for d in names if self.is_excluded(d)]

    def status(self) -> Dict[str, Any]:
        """Full view for the state API / dashboard."""
        now = self._clock()
        out: Dict[str, Any] = {"threshold": self.threshold,
                               "half_life_s": self.half_life_s,
                               "domains": {}}
        with self._lock:
            items = list(self._domains.items())
        for domain, st in items:
            drain_left = None
            if st.drain_deadline is not None:
                drain_left = max(0.0, st.drain_deadline - now)
            out["domains"][domain] = {
                "score": round(self._decayed(st, now), 4),
                "failures": st.failures,
                "trips": st.trips,
                "quarantined": self.is_quarantined(domain),
                "draining": drain_left is not None and drain_left > 0,
                "drain_remaining_s": drain_left,
                "drain_reason": st.drain_reason or None,
                "manual": st.manual,
                "exempt": domain in self.exempt,
                "last_kind": st.last_kind or None,
                "last_detail": st.last_detail or None,
                "last_failure_ts": st.last_failure_ts or None,
                "recent": list(st.recent),
            }
        return out
