"""ray_tpu.resilience: preemption-aware, failure-domain-aware recovery.

TPU pods make preemption and maintenance routine, and the hardest part
of the runtime is behaving well under that churn (SURVEY §7). This
package is the recovery subsystem spanning the node agent, conductor,
trainer, and observability layers:

- :mod:`preemption` — node-side watcher for the maintenance-event
  channel (``RAY_TPU_MAINTENANCE_EVENT`` file/env) and SIGTERM; turns a
  doomed host into a conductor broadcast: "checkpoint now, grace N s".
- :mod:`domains` — per-host failure history with decay; hosts over the
  threshold are quarantined out of lease grants, placement-group
  assignment, and gang re-formation.
- :mod:`supervisor` — gang supervision for workers-mode training: fast
  peer-death detection via the conductor's death pubsub,
  cancel-the-survivors, backoff policy, and elastic re-form onto a
  smaller ``dcn_dp`` axis when capacity shrank.
- :mod:`chaos` — deterministic scriptable fault plans (kill rank R at
  step S, preempt host H with grace G, delay heartbeats, bounce the
  conductor) so integration tests replay exact failure scenarios.

Surfaces: ``ray_tpu.util.state.resilience_status()``, ``python -m
ray_tpu resilience-status``, dashboard ``/api/resilience``, restart/
preemption/quarantine counters, and restart/preemption markers in the
merged flight-recorder timeline.
"""
from .chaos import (  # noqa: F401
    ChaosAction,
    ChaosError,
    ChaosMonkey,
    ChaosPlan,
    monkey_from_spec,
)
from .domains import FailureDomainTracker  # noqa: F401
from .preemption import (  # noqa: F401
    MaintenanceEvent,
    PreemptionWatcher,
    install_sigterm_notifier,
    read_maintenance_event,
)
from .supervisor import (  # noqa: F401
    GangSupervisor,
    backoff_delay,
    elastic_reform,
)
