"""Preemption watcher: the node-side half of graceful preemption.

TPU VMs learn about preemption/maintenance two ways: the runtime's
maintenance-event API (upcoming-maintenance notices with a grace
window), and plain SIGTERM when the platform starts reclaiming the VM.
This module stands in for both with portable channels:

- ``RAY_TPU_MAINTENANCE_EVENT`` names a file; when the file appears the
  host is being preempted. The file may be empty (defaults apply) or
  JSON ``{"grace_s": 30, "reason": "maintenance"}``. Tests and the chaos
  harness touch the file; production glue points the env var at
  whatever the fleet's maintenance notifier writes.
- ``install_sigterm_notifier`` chains a SIGTERM handler in daemon
  processes (the node agent) so a platform kill becomes a conductor
  notification before the process dies.

Either way the payload is the same: the watcher calls ``notify(event)``
once per event, and the node agent forwards it to the conductor's
``report_preemption`` — which broadcasts "checkpoint now, you have N
seconds" to every training session and starts draining the host.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

ENV_VAR = "RAY_TPU_MAINTENANCE_EVENT"


def _default_grace() -> float:
    from ray_tpu._private.config import config

    return config.preempt_grace_s


def _default_poll() -> float:
    from ray_tpu._private.config import config

    return config.maintenance_poll_s


@dataclass
class MaintenanceEvent:
    grace_s: float
    reason: str = "maintenance"
    raw: Optional[dict] = None


def read_maintenance_event(spec: Optional[str] = None
                           ) -> Optional[MaintenanceEvent]:
    """Parse the maintenance channel once. `spec` is the file path
    (default: the env var's value); returns None when no event is
    pending. A malformed file still signals — a preemption notice must
    never be dropped over a JSON typo."""
    spec = spec if spec is not None else os.environ.get(ENV_VAR)
    if not spec:
        return None
    if not os.path.exists(spec):
        return None
    raw: Optional[dict] = None
    try:
        with open(spec) as f:
            text = f.read().strip()
        if text:
            raw = json.loads(text)
    except (OSError, ValueError):
        raw = None
    grace = _default_grace()
    reason = "maintenance"
    if isinstance(raw, dict):
        try:
            grace = float(raw.get("grace_s", grace))
        except (TypeError, ValueError):
            pass
        reason = str(raw.get("reason", reason))
    return MaintenanceEvent(grace_s=grace, reason=reason, raw=raw)


class PreemptionWatcher:
    """Polls the maintenance channel and fires `notify(event)` once per
    event (re-arming only after the file disappears, so a lingering
    notice file does not re-broadcast every poll)."""

    def __init__(self, notify: Callable[[MaintenanceEvent], None],
                 spec: Optional[str] = None,
                 poll_s: Optional[float] = None):
        self._notify = notify
        self._spec = spec
        self._poll_s = poll_s
        self._stopped = threading.Event()
        self._fired = False
        self._thread = threading.Thread(
            target=self._loop, name="preemption-watcher", daemon=True)

    def start(self) -> "PreemptionWatcher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()

    def poll_once(self) -> Optional[MaintenanceEvent]:
        """One poll step (the loop body, exposed for tests): returns the
        event when this call fired the notification."""
        ev = read_maintenance_event(self._spec)
        if ev is None:
            self._fired = False  # channel cleared: re-arm
            return None
        if self._fired:
            return None
        self._fired = True
        try:
            self._notify(ev)
        except Exception:  # noqa: BLE001 — a flaky notify must not
            self._fired = False  # lose the event; retry next poll
            return None
        return ev

    def _loop(self) -> None:
        while not self._stopped.wait(self._poll_s or _default_poll()):
            self.poll_once()


def install_sigterm_notifier(notify: Callable[[MaintenanceEvent], None],
                             grace_s: Optional[float] = None):
    """Chain a SIGTERM handler that reports a preemption (then calls any
    previously-installed handler). For daemon mains only — a library
    must not hijack its host process's signals. Returns the previous
    handler."""
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        try:
            notify(MaintenanceEvent(
                grace_s=grace_s if grace_s is not None else _default_grace(),
                reason="sigterm"))
        except Exception:  # noqa: BLE001 — dying anyway; don't mask prev
            pass
        if callable(prev):
            prev(signum, frame)
        elif prev != signal.SIG_IGN:
            # previous disposition was the default (terminate): restore
            # it and re-raise so the process still dies — notifying must
            # not turn `kill`/`systemctl stop` into a hang-until-SIGKILL
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    signal.signal(signal.SIGTERM, _handler)
    return prev


def preemption_deadline(event: MaintenanceEvent,
                        now: Optional[float] = None) -> float:
    """Wall-clock deadline the grace window ends at."""
    return (now if now is not None else time.time()) + event.grace_s
