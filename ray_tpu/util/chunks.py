"""Shared chunked object-plane transfer: ONE implementation of the
"host array -> owned chunk -> point-to-point fetch" path used by every
subsystem that ships tensors between processes without a gather.

Producers put each host array into THEIR OWN object store as a chunk
(the shm path serves same-host readers zero-copy; remote readers stream
it through the worker's 64MB-ranged `fetch_object_range` pulls) and pass
around only a metadata entry naming the chunk. Consumers rebuild an
``ObjectRef`` from the entry and pull the bytes point-to-point from the
owner — the conductor only ever sees metadata, never payload.

Extracted from ``weights/publisher.py`` / ``weights/subscriber.py`` so
the live weight fabric and the MPMD activation channels
(``ray_tpu.mpmd.channels``) share one implementation — including the
``ascontiguousarray`` guard (it would promote 0-d arrays to 1-d, so
0-d leaves skip it) — with one set of tests (``tests/test_mpmd.py``).

Ownership model (deliberate, matching the object plane): the returned
``ObjectRef``s ARE the chunks' lifetime. Callers must hold them until
every consumer has fetched; dropping the last ref frees the store entry.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu._private.object_store import ObjectRef

# Per-CALLER fabric accounting across every fetcher in this process:
# caller label ("weights" / "kv" / "activations" / "kvplane" / ...) ->
# the same counter set each fetcher keeps. Lets the kvplane surface
# report tier-3 bytes without aliasing them with weight-fabric traffic
# riding the same fabric.
_CALLER_KEYS = ("chunks_local", "chunks_fetched", "fetched_bytes",
                "shm_bytes", "rpc_bytes", "fetch_retries")
_caller_totals: Dict[str, Dict[str, int]] = {}
_caller_lock = threading.Lock()


def caller_totals(caller: Optional[str] = None) -> Dict[str, Any]:
    """Process-wide fetch accounting grouped by caller label — one
    caller's counter dict, or ``{caller: counters}`` for all of them."""
    with _caller_lock:
        if caller is not None:
            return dict(_caller_totals.get(
                caller, {k: 0 for k in _CALLER_KEYS}))
        return {c: dict(v) for c, v in _caller_totals.items()}

# Transient pull failures worth retrying: a timed-out range fetch or a
# connection hiccup to the owning worker. Owner-side permanent failures
# (ObjectLostError: the chunk is gone with its process) re-raise
# immediately — retrying cannot bring the bytes back.
_TRANSIENT = (ConnectionError, EOFError, OSError, TimeoutError)


def _fetch_retries() -> int:
    try:
        return max(0, int(os.environ.get("RAY_TPU_CHUNK_FETCH_RETRIES",
                                         "2")))
    except (TypeError, ValueError):
        return 2


def ensure_chunkable(host_arr: Any) -> np.ndarray:
    """`host_arr` as a C-contiguous ndarray ready for the store.

    NB: ``np.ascontiguousarray`` would promote a 0-d array to 1-d, so
    0-d arrays pass through as-is (they are trivially contiguous)."""
    arr = np.asarray(host_arr)
    if arr.ndim and not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return arr


def local_machine_id() -> str:
    """This HOST's identity (worker._MACHINE_ID): two processes sharing
    it can hand chunks over shm instead of RPC. Chunk entries carry the
    producer's machine id so consumers can account (and prefer) the
    same-host path."""
    from ray_tpu._private.worker import _MACHINE_ID

    return _MACHINE_ID


def put_chunk(worker, host_arr: Any) -> Tuple[Any, Dict[str, Any]]:
    """Put one host array into `worker`'s own store. Returns
    ``(ref, entry)`` — hold `ref` for the chunk's lifetime; `entry` is
    the metadata a consumer needs to fetch it point-to-point (plus the
    array's shape/dtype, so tree descriptors need no second
    conversion pass)."""
    arr = ensure_chunkable(host_arr)
    ref = worker.put(arr)
    entry = {"object_id": ref.id,
             "locator": list(worker.address),
             "machine": local_machine_id(),
             "nbytes": int(arr.nbytes),
             "shape": list(arr.shape),
             "dtype": str(arr.dtype)}
    return ref, entry


class ChunkFetcher:
    """Chunk puller with a per-instance cache: each needed chunk crosses
    the object plane at most once per fetcher, with remote-vs-local
    accounting (``chunks_local`` / ``chunks_fetched`` /
    ``fetched_bytes``), split further into the same-host shm path
    (``shm_bytes``) vs true cross-host RPC (``rpc_bytes``) by comparing
    the entry's producer machine id against ours. Callable with a chunk
    entry dict."""

    def __init__(self, worker, timeout: float = 60.0,
                 on_read: Optional[Callable[[int, bool, bool],
                                            None]] = None,
                 seed_cache: Optional[Dict[str, np.ndarray]] = None,
                 retries: Optional[int] = None,
                 caller: str = "unlabeled"):
        self._worker = worker
        self._timeout = timeout
        # per-caller attribution: which subsystem's traffic this is
        # (weights / kv / activations / kvplane) — feeds caller_totals()
        self.caller = str(caller)
        self._on_read = on_read
        self._machine = local_machine_id()
        # bounded retry-with-backoff on TRANSIENT pull failures (env
        # RAY_TPU_CHUNK_FETCH_RETRIES, default 2): a timed-out range
        # fetch used to fail the whole consumer — KV transfer, weight
        # fetch, activation recv — on one slow owner round-trip
        self._retries = _fetch_retries() if retries is None \
            else max(0, int(retries))
        # seed_cache: chunks something else already pulled (subscriber
        # prefetch) — their first use accounts as a LOCAL read
        self._cache: Dict[str, np.ndarray] = dict(seed_cache or {})
        self._seeded = set(self._cache)
        self.chunks_local = 0
        self.chunks_fetched = 0
        self.fetched_bytes = 0
        self.shm_bytes = 0
        self.rpc_bytes = 0
        self.fetch_retries = 0

    @property
    def cache(self) -> Dict[str, np.ndarray]:
        """The pulled chunks by object id — holdable by a caller to
        keep a version's bytes at hand across fetchers (prefetch)."""
        return self._cache

    def stats(self) -> Dict[str, int]:
        """One accounting snapshot (the no-full-copy evidence every
        consumer of the chunk fabric reports): chunks served locally vs
        pulled point-to-point, and the pulled bytes split same-host shm
        vs cross-host RPC."""
        return {"chunks_local": self.chunks_local,
                "chunks_fetched": self.chunks_fetched,
                "fetched_bytes": self.fetched_bytes,
                "shm_bytes": self.shm_bytes,
                "rpc_bytes": self.rpc_bytes,
                "fetch_retries": self.fetch_retries,
                "caller": self.caller}

    def _account_caller(self, **deltas: int) -> None:
        with _caller_lock:
            tot = _caller_totals.setdefault(
                self.caller, {k: 0 for k in _CALLER_KEYS})
            for k, v in deltas.items():
                tot[k] += v

    def _get_with_retries(self, ref: ObjectRef) -> np.ndarray:
        """One chunk pull with bounded exponential backoff on transient
        failures; every consumer of the chunk fabric (KV transfer,
        weight fetch, activation recv) gets the retry for free."""
        from ray_tpu.resilience.chaos import chunk_fetch_delay_s

        delay = chunk_fetch_delay_s()  # scripted chaos stretch
        if delay > 0:
            time.sleep(delay)
        attempt = 0
        while True:
            try:
                return np.asarray(self._worker.get(
                    ref, timeout=self._timeout))
            except _TRANSIENT:
                if attempt >= self._retries:
                    raise
                attempt += 1
                self.fetch_retries += 1
                self._account_caller(fetch_retries=1)
                time.sleep(min(5.0, 0.1 * 2.0 ** (attempt - 1)))

    def __call__(self, entry: Dict[str, Any]) -> np.ndarray:
        oid = entry["object_id"]
        arr = self._cache.get(oid)
        if arr is not None:
            if oid in self._seeded:
                self._seeded.discard(oid)
                self.chunks_local += 1
                self._account_caller(chunks_local=1)
                if self._on_read is not None:
                    self._on_read(int(entry.get("nbytes", arr.nbytes)),
                                  True, True)
            return arr
        was_local = self._worker.store.contains(oid)
        ref = ObjectRef(oid, locator=tuple(entry["locator"]),
                        owner=tuple(entry["locator"]))
        t_pull = time.perf_counter()
        arr = self._get_with_retries(ref)
        # flight recorder: when a request trace is active on this
        # thread (KV adoption under the router's kv_transfer span) the
        # per-pull wall time accumulates onto the open phase — the
        # chaos delay_chunk_fetch stretch lands HERE, which is what
        # lets the p99 report name kv_transfer as the tail owner
        # (function-level import: util must not import observability
        # at module scope)
        from ray_tpu.observability.requests import annotate

        annotate(pull_ms=round((time.perf_counter() - t_pull) * 1e3, 3),
                 pulls=1)
        nbytes = int(entry.get("nbytes", arr.nbytes))
        # entries predating the machine field read as same-host (shm was
        # the only deployment shape those versions supported)
        same_host = entry.get("machine", self._machine) == self._machine
        if was_local:
            self.chunks_local += 1
            self._account_caller(chunks_local=1)
        else:
            self.chunks_fetched += 1
            self.fetched_bytes += nbytes
            if same_host:
                self.shm_bytes += nbytes
                self._account_caller(chunks_fetched=1,
                                     fetched_bytes=nbytes,
                                     shm_bytes=nbytes)
            else:
                self.rpc_bytes += nbytes
                self._account_caller(chunks_fetched=1,
                                     fetched_bytes=nbytes,
                                     rpc_bytes=nbytes)
        if self._on_read is not None:
            self._on_read(nbytes, was_local, same_host)
        self._cache[oid] = arr
        return arr


# ---------------------------------------------------------- pytree payloads

def put_tree(worker, tree: Any) -> Tuple[List[Any], Dict[str, Any]]:
    """Chunk every leaf of a pytree into `worker`'s store. Returns
    ``(refs, descriptor)``: hold `refs` until consumers fetched; the
    descriptor (leaf entries + pickled treedef) is metadata-only and
    safe to route through the conductor."""
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    refs: List[Any] = []
    entries: List[Dict[str, Any]] = []
    total = 0
    for leaf in leaves:
        ref, entry = put_chunk(worker, leaf)
        refs.append(ref)
        entries.append(entry)
        total += entry["nbytes"]
    descriptor = {"leaves": entries,
                  "treedef": pickle.dumps(treedef, protocol=5),
                  "total_bytes": total}
    return refs, descriptor


def fetch_tree(worker, descriptor: Dict[str, Any],
               fetcher: Optional[ChunkFetcher] = None) -> Any:
    """Materialize a ``put_tree`` descriptor: pull each leaf chunk
    point-to-point from its owner and unflatten."""
    import jax

    if fetcher is None:
        fetcher = ChunkFetcher(worker)
    leaves = [fetcher(entry) for entry in descriptor["leaves"]]
    treedef = pickle.loads(descriptor["treedef"])
    return jax.tree.unflatten(treedef, leaves)


__all__ = ["ChunkFetcher", "caller_totals", "ensure_chunkable",
           "fetch_tree", "local_machine_id", "put_chunk", "put_tree"]
